"""Command-line interface (L6 of SURVEY.md's layer map).

The reference's entire user surface is a stdin REPL on the master ("type a
filename, get output.txt", ``server.c:160-167``) plus conf-file argv
(``server.c:100-103``).  The CLI keeps that workflow (`dsort serve` is the
REPL; conf files in the reference's own format are accepted) and adds the
one-shot, benchmark, data-generation, cluster, and worker entry points a real
tool needs.

  dsort run INPUT [-o OUT]      one sort job (file -> file)
  dsort run --device-resident   same, sorted array stays on the mesh and
                                validates on device (no relay)
  dsort serve                   REPL: filenames on stdin until 'exit'
  dsort bench                   throughput benchmark, one JSON line
  dsort gen N -o FILE           synthetic inputs (uniform / zipf)
  dsort coordinator             native TCP coordinator for a worker cluster
  dsort worker                  worker shim joining a coordinator
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

from dsort_tpu.config import SortConfig
from dsort_tpu.utils.logging import get_logger
from dsort_tpu.utils.metrics import Metrics

# Wedged-on-first-contact latch for the fused small-job path (ADVICE r4).
# The discriminator is the fused LANE, not lapse counts: all fused
# attempts serialize on one lane thread, so "one entry executing for
# longer than any cold compile ever observed" is direct evidence the chip
# is wedged, while any number of cold lapses QUEUED behind a
# still-compiling entry is not.  The ceiling sits 1.5x above the slowest
# cold compile seen through the axon remote Mosaic service (~10 min for
# one K2a shape, r3).
FUSED_COLD_WEDGE_CEILING_S = 900.0
# A cold latch is EVIDENCE, not proof — a pathological compile can outlast
# even the ceiling (the remote service swings ~8x between sessions).  So
# unlike the warm-wedge latch (the executable had run before; the stuck
# lane is proof), the cold latch expires: after this long the path retries
# — if the stall was a compile it has drained and the retry succeeds fast;
# a truly wedged chip lapses again with the lane stuck even longer and
# re-latches on that single lapse.  Worst case on a wedged chip: one cold
# wait budget per interval.
FUSED_COLD_RETRY_S = 1800.0
# Backstop for FAIL-SLOW devices the lane discriminator cannot see (each
# fused call errors after the wait budget but before the ceiling, so the
# lane keeps draining): this many consecutive cold lapses without a single
# fused success latch the path off too.  A false trip during one slow
# compile with many queued jobs is benign — the latch expires and the
# post-drain retry succeeds and resets.
FUSED_COLD_LAPSE_BACKSTOP = 8

log = get_logger("cli")


def _load_config(args) -> SortConfig:
    """Conf file + CLI overrides, applied field-wise.

    Overrides use ``dataclasses.replace`` on the loaded config — NOT a
    rebuild through a key mapping — so a single CLI flag can never silently
    drop conf-file settings it doesn't know about (code-review r3).

    Autotune precedence (obs.plan, ARCHITECTURE §15): ``--no-autotune``
    wins, then an explicit conf ``AUTOTUNE=``, else ON — the CLI defaults
    the closed loop on (the library's `JobConfig` default stays off).  A
    knob flag actually given (``--exchange``, ``--redundancy``,
    ``--prewarm all``) joins ``JobConfig.explicit`` so the planner never
    overrides it — it journals a ``plan_override`` instead.
    """
    import dataclasses

    from dsort_tpu.config import load_conf_file

    conf_map = load_conf_file(args.conf) if args.conf else {}
    cfg = SortConfig.from_mapping(conf_map) if args.conf else SortConfig()
    job_over: dict = {}
    mesh_over: dict = {}
    if getattr(args, "workers", None):
        mesh_over["num_workers"] = args.workers
    if getattr(args, "dtype", None):
        job_over["key_dtype"] = np.dtype(args.dtype)
    if getattr(args, "kernel", None):
        job_over["local_kernel"] = args.kernel
    if getattr(args, "merge_kernel", None):
        job_over["merge_kernel"] = args.merge_kernel
    if getattr(args, "exchange", None):
        job_over["exchange"] = args.exchange
    if getattr(args, "hier_hosts", None):
        job_over["hier_hosts"] = args.hier_hosts
    if getattr(args, "redundancy", None):
        job_over["redundancy"] = args.redundancy
    if getattr(args, "redundancy_mode", None):
        job_over["redundancy_mode"] = args.redundancy_mode
    if getattr(args, "checkpoint_dir", None):
        job_over["checkpoint_dir"] = args.checkpoint_dir
    if getattr(args, "tenant", None):
        job_over["tenant"] = args.tenant
    if getattr(args, "flight_dir", None):
        job_over["flight_recorder_dir"] = args.flight_dir
    if getattr(args, "no_autotune", False):
        job_over["autotune"] = False
    elif "AUTOTUNE" not in conf_map:
        job_over["autotune"] = True
    explicit = set(cfg.job.explicit)
    if getattr(args, "exchange", None):
        explicit.add("exchange")
    if getattr(args, "redundancy", None):
        explicit.add("redundancy")
    if getattr(args, "redundancy_mode", None):
        explicit.add("redundancy_mode")
    if getattr(args, "slice_devices", None):
        explicit.add("slice_devices")
    if getattr(args, "prewarm", None) == "all":
        explicit.add("prewarm")
    if explicit != set(cfg.job.explicit):
        job_over["explicit"] = tuple(sorted(explicit))
    if job_over:
        cfg = dataclasses.replace(cfg, job=dataclasses.replace(cfg.job, **job_over))
    if mesh_over:
        cfg = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, **mesh_over)
        )
    return cfg


def _job_id_for(path: str, explicit: str | None) -> str:
    """Stable checkpoint job id for a CLI input file.

    Defaults to the sanitized basename, so re-running ``dsort run FILE``
    after a failure resumes FILE's own checkpoints; the fingerprint guard in
    the schedulers clears stale state if FILE's contents changed.  An
    explicit id is validated, not silently rewritten: ids like ``..`` would
    escape the checkpoint root (and its stale-state clear() would rmtree
    the parent), so they are refused loudly.
    """
    import re

    if explicit:
        if re.fullmatch(r"[A-Za-z0-9._-]+", explicit) and explicit.strip("."):
            return explicit
        raise SystemExit(
            f"invalid --job-id {explicit!r}: use letters, digits, '.', '_', "
            "'-' (and not only dots)"
        )
    name = os.path.basename(str(path))
    jid = re.sub(r"[^A-Za-z0-9._-]", "_", name)
    return jid if jid.strip(".") else "job"


def _make_sorter(cfg: SortConfig, mode: str):
    """Build the sort callable for one of the execution modes."""
    if mode == "spmd":
        from dsort_tpu.models.pipelines import FUSED_SMALL_JOB_MAX, fused_sort_small
        from dsort_tpu.scheduler import SpmdScheduler

        import jax

        devs = jax.devices()
        n = cfg.mesh.num_workers or len(devs)
        sched = SpmdScheduler(devices=devs[:n], job=cfg.job)
        # Once a fused attempt wedges, its lane thread is stuck for the
        # process lifetime and the lane key never changes — skip the fused
        # path from then on instead of paying a full wait budget per job.
        fused_wedged = threading.Event()
        # A chip genuinely wedged on FIRST contact never warms the fused
        # (lane,size) bucket, so every lapse stays "cold" and the
        # compile-grace exemption below would retry forever (ADVICE r4).
        # Bound it with the lane-stuck discriminator (see the module
        # constants): once the fused lane has been inside ONE entry for
        # longer than any compile ever observed, latch the path off until
        # the retry interval expires.
        fused_cold_latch_ts = [0.0]  # 0 = cold latch inactive
        fused_cold_streak = [0]  # consecutive cold lapses since a success

        def fused_path_open() -> bool:
            if fused_wedged.is_set():
                return False  # warm wedge: permanent (stuck proven lane)
            ts = fused_cold_latch_ts[0]
            return not ts or time.monotonic() - ts > FUSED_COLD_RETRY_S

        def sorter(data, metrics, job_id=None):
            # Small jobs skip the SPMD driver: one fused device program is
            # ~2 dispatches instead of ~7, which dominates at this size
            # (VERDICT r2 item 3).  Fault tolerance is preserved: a device/
            # runtime failure on the fused path falls back to the SPMD
            # scheduler, which probes, re-forms and retries.  When the user
            # asked for checkpointing (checkpoint_dir + job_id), the
            # scheduler path runs even for small jobs — resumability wins
            # over dispatch count there.
            checkpointing = cfg.job.checkpoint_dir and job_id
            # A coded job (redundancy > 1) must reach the exchange plane:
            # the fused single-device shortcut has no replica plane, and
            # silently dropping an explicit availability posture would be
            # worse than the extra dispatches — same rule as checkpointing.
            if (
                len(data) < FUSED_SMALL_JOB_MAX
                and not checkpointing
                and cfg.job.redundancy <= 1
                and fused_path_open()
            ):
                try:
                    # run_bounded: the fused program's completion barrier
                    # (the result fetch inside fused_sort_small) is covered
                    # by the same in-flight hang detection as the SPMD
                    # collective (VERDICT r3 #1) — a wedged chip makes this
                    # time out and fall back, never block forever.
                    metrics.event(
                        "job_start", mode="fused", n_keys=len(data),
                        job_id=job_id, tenant=cfg.job.tenant,
                    )
                    out = sched.run_bounded(
                        lambda: fused_sort_small(
                            data, cfg.job.local_kernel, metrics
                        ),
                        n_keys=len(data), tag="fused",
                    )
                    metrics.bump("fused_small_jobs")
                    metrics.event(
                        "job_done", n_keys=len(data),
                        counters=dict(metrics.counters),
                    )
                    fused_cold_latch_ts[0] = 0.0
                    fused_cold_streak[0] = 0
                    return out
                except Exception as e:
                    from dsort_tpu.scheduler.fault import (
                        ProgramWaitTimeout,
                        classify_runtime_error,
                    )

                    if not isinstance(e, ProgramWaitTimeout) and (
                        classify_runtime_error(e) is None
                    ):
                        raise  # genuine program error, not a device loss/hang
                    if isinstance(e, ProgramWaitTimeout) and not getattr(
                        e, "cold", False
                    ):
                        # Only a WARM lapse (the fused executable had
                        # completed here before) latches the path off — a
                        # cold lapse is likely the one-time compile running
                        # long (observed r4: ~5 min vs a 30-150 s grace);
                        # the compile continues on its lane, warms the jit
                        # cache, and the next small job tries fused again.
                        fused_wedged.set()
                    elif isinstance(e, ProgramWaitTimeout):
                        stuck = sched.lane_stuck_for("fused")
                        # The streak resets ONLY on a fused success: a
                        # sustained fail-slow device re-latches on the
                        # single post-expiry retry lapse (streak still at
                        # the backstop), matching the wedged-chip path's
                        # one-budget-per-interval worst case.  Wedged-chip
                        # diagnosis (lane stuck) is checked first so the
                        # log names the right failure mode.
                        fused_cold_streak[0] += 1
                        if stuck > FUSED_COLD_WEDGE_CEILING_S:
                            log.warning(
                                "fused path latched off for %.0f s: the "
                                "fused lane has been inside one entry for "
                                "%.0f s (past the %.0f s compile ceiling "
                                "— chip wedged on first contact, not "
                                "compiling)", FUSED_COLD_RETRY_S, stuck,
                                FUSED_COLD_WEDGE_CEILING_S,
                            )
                            fused_cold_latch_ts[0] = time.monotonic()
                        elif (
                            fused_cold_streak[0]
                            >= FUSED_COLD_LAPSE_BACKSTOP
                        ):
                            log.warning(
                                "fused path latched off for %.0f s: %d "
                                "consecutive cold wait lapses without a "
                                "fused success (fail-slow device backstop)",
                                FUSED_COLD_RETRY_S, fused_cold_streak[0],
                            )
                            fused_cold_latch_ts[0] = time.monotonic()
                    metrics.bump("fused_fallbacks")
                    metrics.event(
                        "fused_fallback",
                        reason=str(e).splitlines()[0][:120],
                    )
                    log.warning(
                        "fused small-job path failed (%s); retrying on the "
                        "SPMD scheduler", str(e).splitlines()[0][:120],
                    )
            return sched.sort(data, metrics=metrics, job_id=job_id)

        return sorter
    if mode == "taskpool":
        from dsort_tpu.scheduler import DeviceExecutor, Scheduler

        import jax

        devs = jax.devices()
        n = cfg.mesh.num_workers or len(devs)
        sched = Scheduler(DeviceExecutor(devices=devs[:n]), cfg.job)
        return lambda data, metrics, job_id=None: sched.run_job(
            data, metrics=metrics, job_id=job_id
        )
    if mode == "local":
        from dsort_tpu.models.pipelines import fused_sort_small

        if cfg.job.checkpoint_dir:
            log.warning(
                "--mode local runs one fused device program and does not "
                "checkpoint; --checkpoint-dir/--job-id are ignored (use "
                "spmd or taskpool mode for resumable jobs)"
            )

        def local_sorter(data, metrics, job_id=None):
            # Journal the job boundaries here too: local mode has no
            # scheduler to emit them, and without job_start/job_done the
            # SLO tracker (obs.slo) cannot see local-mode jobs at all.
            metrics.event(
                "job_start", mode="local", n_keys=len(data), job_id=job_id,
                tenant=cfg.job.tenant,
            )
            out = fused_sort_small(data, cfg.job.local_kernel, metrics)
            metrics.event(
                "job_done", n_keys=len(data), counters=dict(metrics.counters)
            )
            return out

        return local_sorter
    raise SystemExit(f"unknown mode {mode!r}")


def _run_one(
    sorter, in_path: str, out_path: str, dtype, job_id=None, journal=None,
    telemetry=None, args=None,
) -> None:
    from dsort_tpu.data.ingest import read_ints_file, write_ints_file

    t0 = time.perf_counter()
    data = read_ints_file(in_path, dtype=dtype)
    metrics = Metrics(journal=journal)
    if telemetry is not None:
        telemetry.attach(metrics)
    if args is not None:
        _maybe_memwatch(args, metrics)
    try:
        out = sorter(data, metrics, job_id=job_id)
    except BaseException as e:
        # The schedulers emit job_failed only on their CLEAN failure paths
        # (all workers dead); any other escape after job_start would leave
        # the job open forever on the telemetry side — jobs_in_flight
        # inflated for the rest of a serve session.  A duplicate
        # job_failed (scheduler already emitted one) is a no-op for the
        # taps, so closing unconditionally here is safe.
        metrics.event(
            "job_failed", reason=(str(e).splitlines() or [repr(e)])[0][:120],
            counters=dict(metrics.counters),
        )
        raise
    # The 'fetched' SLO stage boundary: on the relay path the sorted keys
    # are host-resident exactly here (obs.slo — sorted_to_fetched).
    metrics.event("result_fetch", n_keys=len(out))
    write_ints_file(out_path, out)
    dt = time.perf_counter() - t0
    log.info(
        "sorted %d keys in %.1f ms (%s) -> %s | phases: %s | %s",
        len(data), dt * 1e3, in_path, out_path, metrics.summary()["phases_ms"],
        dict(metrics.counters),
    )


def _open_journal(args):
    """An `EventLog` when ``--journal PATH`` was given, else None.

    ``--journal-rotate-mb N`` bounds any one JSONL file: at the threshold
    the flushed file rotates to ``path.N`` (`EventLog` docs) and ``dsort
    report`` stitches the set back together.
    """
    if not getattr(args, "journal", None):
        return None
    from dsort_tpu.utils.events import EventLog

    mb = getattr(args, "journal_rotate_mb", None)
    return EventLog(rotate_bytes=int(mb * (1 << 20)) if mb else None)


def _maybe_memwatch(args, metrics) -> None:
    """Attach the HBM-watermark tap (``--memwatch``) to a job's metrics."""
    if getattr(args, "memwatch", False):
        from dsort_tpu.obs.prof import MemWatch

        MemWatch().attach(metrics)


def _write_journal(journal, args) -> None:
    if journal is not None:
        # Append-only flush: serve/coordinator call this after EVERY job of
        # a session, and rewriting the whole file each time would be
        # O(session^2) IO.
        journal.flush_jsonl(args.journal)
        log.info("event journal written to %s (%d events)",
                 args.journal, len(journal))


def _make_device_scheduler(cfg: SortConfig):
    """The `SpmdScheduler` behind every ``--device-resident`` entry point."""
    from dsort_tpu.scheduler import SpmdScheduler

    import jax

    devs = jax.devices()
    n = cfg.mesh.num_workers or len(devs)
    return SpmdScheduler(devices=devs[:n], job=cfg.job)


def _run_one_device(
    cfg, in_path: str, out_path: str, dtype, journal, args=None
) -> int:
    """One device-resident job: sort, validate on device, then write.

    The sorted array never relays to the host for validation — the order
    check and the FNV multiset checksum run as jitted reductions on the
    mesh, and the permutation proof compares the device checksum against
    the (already host-resident) input's checksum.  The single D2H is the
    explicit ``to_host()`` that feeds the output file the `run` contract
    requires.
    """
    from dsort_tpu.data.ingest import read_ints_file, write_ints_file
    from dsort_tpu.models.validate import _multiset

    if cfg.job.checkpoint_dir:
        # Surface the semantics change up front (the scheduler's own warning
        # only fires when a job_id reaches it): device-resident jobs do not
        # persist ranges — a crash re-runs the whole job.
        log.warning(
            "--device-resident does not checkpoint: --checkpoint-dir/"
            "--job-id are ignored; a failed job re-runs from the input"
        )
    sched = _make_device_scheduler(cfg)
    t0 = time.perf_counter()
    data = read_ints_file(in_path, dtype=dtype)
    metrics = Metrics(journal=journal)
    if args is not None:
        _maybe_memwatch(args, metrics)
    handle = sched.sort(data, metrics=metrics, keep_on_device=True)
    rep = handle.validate_on_device()
    in_sum = _multiset(data, len(data), data.dtype.itemsize)
    perm_ok = rep.records == len(data) and rep.checksum == in_sum
    write_ints_file(out_path, handle.to_host())
    dt = time.perf_counter() - t0
    log.info(
        "sorted %d keys in %.1f ms (%s, device-resident) -> %s | on-device "
        "validate: sorted=%s permutation=%s checksum=%016x | phases: %s | %s",
        len(data), dt * 1e3, in_path, out_path, rep.sorted_ok, perm_ok,
        rep.checksum, metrics.summary()["phases_ms"], dict(metrics.counters),
    )
    if not (rep.sorted_ok and perm_ok):
        log.error("on-device validation FAILED for %s", in_path)
        return 1
    return 0


def cmd_run(args) -> int:
    from dsort_tpu.utils.tracing import profile_trace

    cfg = _load_config(args)
    if getattr(args, "device_resident", False):
        if args.mode != "spmd":
            raise SystemExit("--device-resident requires --mode spmd")
        journal = _open_journal(args)
        try:
            with profile_trace(getattr(args, "profile_dir", None)):
                return _run_one_device(
                    cfg, args.input, args.output or cfg.output_path,
                    np.dtype(cfg.job.key_dtype), journal, args=args,
                )
        finally:
            _write_journal(journal, args)
    sorter = _make_sorter(cfg, args.mode)
    job_id = (
        _job_id_for(args.input, args.job_id) if cfg.job.checkpoint_dir else None
    )
    journal = _open_journal(args)
    try:
        with profile_trace(getattr(args, "profile_dir", None)):
            _run_one(
                sorter, args.input, args.output or cfg.output_path,
                np.dtype(cfg.job.key_dtype), job_id=job_id, journal=journal,
                args=args,
            )
    finally:
        # The journal exists to answer "what happened" — a failed job's
        # fault timeline must land on disk too.
        _write_journal(journal, args)
    if getattr(args, "profile_dir", None):
        log.info("profiler trace written to %s", args.profile_dir)
    return 0


def _sigterm_to_interrupt(signum, frame):
    """SIGTERM handler for ``dsort serve``: route the signal into the SAME
    graceful path as Ctrl-C (drain in-flight jobs, reject new admissions,
    flush the journal, exit 0) instead of dying mid-job with an open
    journal."""
    raise KeyboardInterrupt


def _make_serve_service(args, cfg, journal, telemetry):
    """The `serve.SortService` behind ``dsort serve`` (every mode).

    spmd mode gets the full serving core — mesh-slice packing for small
    jobs, the SPMD scheduler for big ones, the compiled-variant cache;
    local/taskpool modes wrap their one-job sorter as the service runner,
    keeping admission, fairness, and graceful shutdown semantics uniform.
    """
    import dataclasses

    from dsort_tpu.serve import SortService
    from dsort_tpu.serve.fair import parse_weights

    serve_over: dict = {}
    if getattr(args, "slice_devices", None):
        serve_over["slice_devices"] = args.slice_devices
    if getattr(args, "queue_limit", None):
        serve_over["max_queue_depth"] = args.queue_limit
    if getattr(args, "tenant_limit", None):
        serve_over["max_tenant_inflight"] = args.tenant_limit
    if getattr(args, "weights", None):
        serve_over["tenant_weights"] = parse_weights(args.weights)
    if getattr(args, "slo_shed_ms", None):
        serve_over["slo_shed_ms"] = args.slo_shed_ms
    # ``--prewarm`` (no value / "auto") predicts the set from the planner's
    # admission history; ``--prewarm all`` keeps the old exhaustive ladder
    # (obs.plan's prewarm policy, ARCHITECTURE §15).
    if getattr(args, "prewarm", None) == "all":
        serve_over["prewarm_policy"] = "all"
    serve_cfg = dataclasses.replace(cfg.serve, **serve_over)
    kwargs = dict(
        job=cfg.job, serve=serve_cfg, telemetry=telemetry, journal=journal,
        journal_path=getattr(args, "journal", None),
    )
    if args.mode == "spmd":
        import jax

        devs = jax.devices()
        n = cfg.mesh.num_workers or len(devs)
        service = SortService(devices=devs[:n], **kwargs)
    else:
        service = SortService(runner=_make_sorter(cfg, args.mode), **kwargs)
    if getattr(args, "prewarm", None) or serve_cfg.prewarm:
        n = service.prewarm()
        log.info("compiled-variant cache prewarmed: %d rung(s)", n)
    return service


def cmd_serve(args) -> int:
    """The reference's interactive job loop (server.c:160-167 workflow),
    served by the multi-tenant async core (`dsort_tpu.serve`).

    Each input line submits a job through admission control; with
    ``--max-in-flight 1`` (the default) the REPL awaits each result —
    byte-compatible with the old blocking loop — while ``--max-in-flight
    N`` lets N jobs run concurrently (small jobs packed onto mesh
    sub-slices, big jobs on the full mesh).  A line may name its tenant:
    ``tenant=acme data.txt``.  SIGINT/SIGTERM drain in-flight jobs, reject
    new admissions with a typed verdict, flush the journal, and exit 0.

    ``--metrics-port`` additionally exposes the live telemetry endpoint
    (`obs.MetricsServer`): Prometheus text at ``/metrics`` (counters,
    queue depth, per-tenant admission verdicts and SLO quantiles,
    compiled-variant cache stats), JSON at ``/json``; render a scrape
    with ``dsort top``.
    """
    import signal

    cfg = _load_config(args)
    dtype = np.dtype(cfg.job.key_dtype)
    journal = _open_journal(args)
    if args.job_id and cfg.job.checkpoint_dir:
        # One explicit id across many REPL inputs would make every new file
        # clear the previous file's checkpoints (fingerprint mismatch) —
        # the per-file derived id is the only sane namespace here.
        log.warning(
            "serve mode ignores --job-id: each input file checkpoints under "
            "its own name"
        )
    telemetry = server = None
    if getattr(args, "metrics_port", None) is not None:
        from dsort_tpu.obs import MetricsServer, Telemetry

        telemetry = Telemetry()
        server = MetricsServer(telemetry, port=args.metrics_port)
        log.info("metrics endpoint: %s (render with `dsort top %s`)",
                 server.url, server.url)
    service = _make_serve_service(args, cfg, journal, telemetry)
    old_term = None
    try:
        old_term = signal.signal(signal.SIGTERM, _sigterm_to_interrupt)
    except ValueError:
        pass  # not the main thread (tests): Ctrl-C path still covered
    try:
        return _serve_loop(args, cfg, service, dtype, journal)
    finally:
        if old_term is not None:
            signal.signal(signal.SIGTERM, old_term)
        if server is not None:
            server.close()


def _parse_serve_line(line: str, default_tenant: str) -> tuple[str, str]:
    """``[tenant=NAME] path`` -> (tenant, path)."""
    name = line.strip()
    tenant = default_tenant
    if name.startswith("tenant="):
        head, _, rest = name.partition(" ")
        tenant = head[len("tenant="):]
        name = rest.strip()
    return tenant, name


def _serve_loop(args, cfg, service, dtype, journal) -> int:
    from dsort_tpu.data.ingest import read_ints_file, write_ints_file

    out_path = args.output or cfg.output_path
    max_in_flight = max(getattr(args, "max_in_flight", 1) or 1, 1)
    pending: list[tuple[str, float, object]] = []  # (name, t0, ticket)

    def reap(limit: int) -> None:
        """Write out finished tickets (FIFO); block on the OLDEST only
        while ``limit`` or more jobs are in flight — the window refills
        one slot at a time instead of draining in batch waves."""
        while pending:
            name, t0, ticket = pending[0]
            if len(pending) < limit and not ticket.done():
                break
            pending.pop(0)
            try:
                out = ticket.result()
            except Exception as e:  # a bad job must not kill the server
                log.error("job failed (%s): %s", name, e)
                continue
            try:
                write_ints_file(out_path, out)
            except OSError as e:  # nor an unwritable output path
                log.error("result write failed (%s): %s", name, e)
                continue
            log.info(
                "sorted %d keys in %.1f ms (%s, tenant %s) -> %s | %s",
                len(out), (time.perf_counter() - t0) * 1e3, name,
                ticket.tenant, out_path, dict(ticket.metrics.counters),
            )

    interrupted = False
    while True:
        try:
            line = input("Enter the filename to sort (or 'exit' to quit): ")
        except EOFError:
            break
        except KeyboardInterrupt:
            # The graceful-shutdown path (SIGINT, and SIGTERM via
            # `_sigterm_to_interrupt`): no traceback spray — drain below.
            print()
            interrupted = True
            break
        tenant, name = _parse_serve_line(line, cfg.job.tenant)
        if not name:
            continue
        if name == "exit":
            break
        try:
            data = read_ints_file(name, dtype=dtype)
        except Exception as e:  # unreadable input must not kill the server
            log.error("job failed (%s): %s", name, e)
            continue
        jid = _job_id_for(name, None) if cfg.job.checkpoint_dir else None
        verdict, ticket = service.submit(
            data, tenant=tenant, job_id=name, ckpt_job_id=jid
        )
        if not verdict.admitted:
            log.error(
                "job NOT admitted (%s): %s (queue depth %d, tenant depth %d)",
                name, verdict.reason, verdict.queue_depth,
                verdict.tenant_depth,
            )
            continue
        pending.append((name, time.perf_counter(), ticket))
        # Sync mode (default) awaits every job — the reference's blocking
        # REPL semantics; async mode keeps up to max_in_flight jobs
        # running and frees one slot before prompting again.  Journal
        # flushing is the SERVICE's job (one writer): it appends after
        # every completion.
        reap(limit=max_in_flight)
    if interrupted:
        st = service.stats()
        log.warning(
            "shutting down: draining %d queued + %d in-flight job(s); new "
            "admissions are rejected with verdict 'shutting_down'",
            st["queued"], st["in_flight"],
        )
    service.shutdown(drain=True)
    reap(limit=1)  # drain: every remaining ticket is done or failed
    # The journal's close: the service recorded serve_stop and flushed the
    # file during shutdown; a journal-less session has nothing to write.
    return 0


def cmd_fleet_agent(args) -> int:
    """Run one fleet execution agent: a process owning a mesh (or mesh
    slice), serving jobs routed to it by a `dsort fleet` controller over
    the framed-JSON fleet protocol (ARCHITECTURE §12).

    Wraps the full serving core (`serve.SortService` — slice packing,
    variant cache, eviction/readmission) behind a TCP endpoint; the
    agent advertises its compiled-variant/ledger keys in heartbeats so
    the controller can route by cache locality.  ``--metrics-port``
    exposes the live per-mesh telemetry (`dsort top URL1 URL2 ...`
    renders the fleet view).  SIGINT/SIGTERM DRAIN: in-flight and queued
    jobs complete (results held for the controller), new fleet submits
    are refused with the typed ``shutting_down`` verdict, and the agent
    exits 0.
    """
    import signal

    from dsort_tpu.fleet.agent import FleetAgent

    cfg = _load_config(args)
    journal = _open_journal(args)
    telemetry = server = None
    if getattr(args, "metrics_port", None) is not None:
        from dsort_tpu.obs import MetricsServer, Telemetry

        telemetry = Telemetry()
        server = MetricsServer(telemetry, port=args.metrics_port)
        log.info("agent metrics endpoint: %s", server.url)
    service = _make_serve_service(args, cfg, journal, telemetry)
    agent = FleetAgent(
        service=service, host=args.host, port=args.port,
        agent_id=args.agent_id, journal=journal,
        journal_path=getattr(args, "journal", None),
    )
    print(f"fleet agent {agent.agent_id} listening on {agent.addr}",
          flush=True)
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    old = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            old[sig] = signal.signal(sig, _on_term)
        except ValueError:
            pass  # not the main thread (tests)
    try:
        stop.wait()
        log.warning("agent %s draining before exit", agent.agent_id)
        agent.close(drain=True)
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
        if journal is not None:
            _write_journal(journal, args)
        if server is not None:
            server.close()
    return 0


def cmd_fleet(args) -> int:
    """The fleet controller REPL: `dsort serve`'s workflow, routed over
    many mesh-owning agent processes (ARCHITECTURE §12).

    A pure control plane — admission, weighted-DRR fairness, SLO
    shedding, variant-cache-locality routing.  The CONTROLLER LIBRARY
    (`fleet.controller`) never imports a backend (test-pinned); this CLI
    wrapper does touch jax config for the shared `dsort` config surface —
    embed `FleetController` directly for a truly backend-free process.
    Each input line submits a job (``tenant=acme data.txt``)
    which is spooled, queued, and dispatched to an agent from
    ``--agents host:port,...`` (conf ``FLEET_AGENTS``).  With
    ``--state-dir`` every transition persists, so a controller restart
    loses no job: in-flight work keeps running on its agents and
    re-attaches via the journaled job ids; queued jobs drain in the same
    DRR order.  ``--routing random`` is the locality A/B baseline.
    SIGINT/SIGTERM drain like ``dsort serve``.
    """
    import dataclasses
    import signal

    from dsort_tpu.fleet.controller import FleetController
    from dsort_tpu.serve.fair import parse_weights

    cfg = _load_config(args)
    dtype = np.dtype(cfg.job.key_dtype)
    journal = _open_journal(args)
    fleet_cfg = cfg.fleet
    if getattr(args, "state_dir", None):
        fleet_cfg = dataclasses.replace(fleet_cfg, state_dir=args.state_dir)
    if getattr(args, "routing", None):
        fleet_cfg = dataclasses.replace(fleet_cfg, routing=args.routing)
    if getattr(args, "dispatch_timeout", None):
        fleet_cfg = dataclasses.replace(
            fleet_cfg, dispatch_timeout_s=args.dispatch_timeout
        )
    if getattr(args, "no_health_telemetry", False):
        fleet_cfg = dataclasses.replace(fleet_cfg, telemetry=False)
    agents = getattr(args, "agents", None) or ",".join(fleet_cfg.agents)
    if not agents:
        raise SystemExit(
            "dsort fleet needs --agents host:port,... (or conf FLEET_AGENTS)"
        )
    telemetry = server = None
    if getattr(args, "metrics_port", None) is not None:
        from dsort_tpu.obs import MetricsServer, Telemetry

        telemetry = Telemetry()
        server = MetricsServer(telemetry, port=args.metrics_port)
        log.info("controller metrics endpoint: %s", server.url)
    controller = FleetController(
        agents,
        state_dir=fleet_cfg.state_dir,
        max_queue_depth=args.queue_limit or cfg.serve.max_queue_depth,
        max_tenant_inflight=args.tenant_limit or cfg.serve.max_tenant_inflight,
        drr_quantum_keys=cfg.serve.drr_quantum_keys,
        tenant_weights=(
            parse_weights(args.weights) if getattr(args, "weights", None)
            else dict(cfg.serve.tenant_weights)
        ),
        slo_shed_ms=args.slo_shed_ms or cfg.serve.slo_shed_ms,
        routing=fleet_cfg.routing,
        heartbeat_s=fleet_cfg.heartbeat_s,
        dispatch_timeout_s=fleet_cfg.dispatch_timeout_s,
        default_tenant=cfg.job.tenant,
        journal=journal,
        journal_path=getattr(args, "journal", None),
        telemetry=telemetry,
        health_telemetry=fleet_cfg.telemetry,
        flight_dir=cfg.job.flight_recorder_dir,
        # Closed-loop redundancy (obs.plan policy 3): with autotune on and
        # no explicit --redundancy/conf REDUNDANCY, each dispatch stamps a
        # planned r from the rolling health verdicts; an explicit value is
        # forwarded as-is and journals a plan_override per dispatch.
        autotune=cfg.job.autotune,
        redundancy=(
            cfg.job.redundancy if cfg.job.is_explicit("redundancy") else None
        ),
        redundancy_mode=(
            cfg.job.redundancy_mode
            if cfg.job.is_explicit("redundancy_mode") else None
        ),
    )
    if controller.stats()["agents"] == 0:
        log.warning(
            "no agents reachable: submissions are REJECTED with the typed "
            "verdict 'no_capacity' until one connects (heartbeat retries "
            "every %.1fs)", fleet_cfg.heartbeat_s,
        )
    old_term = None
    try:
        old_term = signal.signal(signal.SIGTERM, _sigterm_to_interrupt)
    except ValueError:
        pass
    try:
        # The controller implements the SortService REPL surface (submit/
        # stats/shutdown + future-style tickets), so the serve loop drives
        # it unchanged — one copy of the REPL contract.
        return _serve_loop(args, cfg, controller, dtype, journal)
    finally:
        if old_term is not None:
            signal.signal(signal.SIGTERM, old_term)
        if server is not None:
            server.close()


_REF_KEYS_PER_SEC = 16_384 / 0.374  # BASELINE.md measured reference throughput


def _bench_suite(args) -> int:
    """The BASELINE config ladder, one JSON line per config.

    1. the reference's own workload (its 16,384-key maximum, ``server.c:13``)
    2. 1M uniform int32, SPMD sample sort over the local mesh
    3. 1M uniform int64 (needs x64; cli.main enabled it)
    4. TeraSort records (full 10-byte key + 90 B payload), kv shuffle
    5. 1M Zipf-skewed keys WITH one injected worker failure
    """
    import jax

    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf, terasort_secondary
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    mesh = local_device_mesh()
    reps = args.reps
    if reps < 1:  # bench.py calls _bench_suite directly, not via cmd_bench
        raise SystemExit("--reps must be >= 1")
    # bench.py passes its recording emitter so the ladder lines join the
    # artifact's final summary line; standalone `dsort bench` just prints.
    emit = getattr(args, "emit", None) or (
        lambda line: print(json.dumps(line), flush=True)
    )

    def timed(label, n, unit, fn, **extra):
        fn()  # warm/compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        # min, not median: tunnel jitter is one-sided additive noise (same
        # doctrine as bench.py's chain timing), and the ladder's end-to-end
        # numbers were swinging ~3x between runs on the median.
        dt = float(min(times))
        line = {
            "metric": label,
            "value": round(n / dt, 1),
            "unit": unit,
            # host->host timing of the public API: includes device dispatch
            # and (through the axon tunnel) a ~0.1-0.6 s relay round-trip,
            # which dominates the small configs — see README "Performance".
            "includes_host_roundtrip": True,
        }
        if unit == "keys/sec":
            # rec/sec vs the reference's keys/sec is not apples-to-apples;
            # only same-unit configs get a vs_baseline ratio (ADVICE r1).
            line["vs_baseline"] = round(n / dt / _REF_KEYS_PER_SEC, 2)
        line.update(extra)
        emit(line)

    ss32 = SampleSort(mesh)
    ref = gen_uniform(16_384, seed=0)
    # Config 1 routes exactly as `dsort run` would (the CLI's small-job
    # auto-route, VERDICT r2 item 3): ONE fused device program — the whole
    # reference job (server.c:160-268) in ~2 tunnel round trips.
    from dsort_tpu.models.pipelines import fused_sort_small

    # Floor decomposition for the one head-to-head row the reference
    # defines (VERDICT r5 next #8): `device_ms` is the pure executable cost
    # (slope over k back-to-back runs on device-resident input — queued
    # executions amortize dispatch, one fetch at the end), and
    # `fixed_overhead_ms_per_dispatch` is the e2e single-job wall minus
    # that — the tunnel round-trip + dispatch floor the headline ratio is
    # actually bound by.  Attributable from the artifact alone.  The e2e
    # reps measured here ARE the config1 line (emitted inline in timed()'s
    # shape) — re-running them through timed() would double config1's wall
    # cost for the same min.
    c1_label = "config1_reference_workload_16384_int32"
    try:
        from dsort_tpu.models.pipelines import _fused_small_fn

        import jax as _jax

        n1 = len(ref)
        f1 = _fused_small_fn(n1, str(ref.dtype), "auto")  # n1 is 2^14: no pad
        # DEVICE-resident input: a host buffer would re-pay H2D on every
        # chained call and inflate device_ms with transfer cost.
        buf1 = _jax.device_put(np.ascontiguousarray(ref))
        np.asarray(f1(buf1, np.int32(n1))[-1:])  # warm/compile

        def _dev_total(k: int) -> float:
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(k):
                    y = f1(buf1, np.int32(n1))
                np.asarray(y[-1:])
                times.append(time.perf_counter() - t0)
            return float(min(times))

        device_s = max((_dev_total(10) - _dev_total(2)) / 8, 0.0)
        fused_sort_small(ref)  # warm the host-path wrapper
        e2e_times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fused_sort_small(ref)
            e2e_times.append(time.perf_counter() - t0)
        e2e_s = float(min(e2e_times))  # min: one-sided tunnel jitter
        emit({
            "metric": c1_label,
            "value": round(n1 / e2e_s, 1),
            "unit": "keys/sec",
            "includes_host_roundtrip": True,
            "vs_baseline": round(n1 / e2e_s / _REF_KEYS_PER_SEC, 2),
            "mode": "fused_local",
            "device_ms": round(device_s * 1e3, 3),
            "fixed_overhead_ms_per_dispatch": round(
                max(e2e_s - device_s, 0.0) * 1e3, 2
            ),
        })
    except Exception as e:  # decomposition must never sink the ladder
        log.warning("config1 floor decomposition failed: %s", e)
        timed(c1_label, len(ref), "keys/sec",
              lambda: fused_sort_small(ref), mode="fused_local")
    u32 = gen_uniform(1 << 20, seed=1)
    timed("config2_uniform_1M_int32_spmd", len(u32), "keys/sec",
          lambda: ss32.sort(u32))
    u64 = gen_uniform(1 << 20, dtype=np.int64, seed=2)
    ss64 = SampleSort(mesh, JobConfig(key_dtype=np.int64))
    timed("config3_uniform_1M_int64_spmd", len(u64), "keys/sec",
          lambda: ss64.sort(u64))
    tk, tv = gen_terasort(1 << 16, seed=3)
    tsec = terasort_secondary(tv)
    sst = SampleSort(mesh, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1]))
    timed("config4_terasort_65536_records_kv", len(tk), "rec/sec",
          lambda: sst.sort_kv(tk, tv, secondary=tsec))
    z = gen_zipf(1 << 20, a=1.3, seed=4)
    if len(jax.devices()) >= 4:
        # One scheduler reused across reps (its per-device-set SampleSort
        # cache keeps the SPMD programs compiled); the injector re-arms each
        # call so EVERY rep really recovers from a failure — verified below.
        inj = FaultInjector()
        sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01), injector=inj)

        def faulted():
            inj.fail_once(2, "spmd")
            m = Metrics()
            sched.sort(z, metrics=m)
            if not m.counters.get("mesh_reforms"):
                raise RuntimeError("config5: injected failure did not fire")

        timed("config5_zipf_1M_with_injected_failure", len(z), "keys/sec",
              faulted)
    else:
        # Injection needs a mesh to lose a device from; on a single-device
        # host the 'with failure' label would be a lie — measure and say so.
        log.warning("config5: <4 devices, failure injection inactive")
        ss5 = SampleSort(mesh)
        timed("config5_zipf_1M_no_failure_single_device", len(z), "keys/sec",
              lambda: ss5.sort(z))
    return 0


def _bench_device_resident(args, cfg: SortConfig) -> int:
    """`dsort bench --device-resident`: the no-relay e2e + validate lines.

    Times (a) device-resident sort — handle creation is already
    synchronized by the retry-scalar fetch, so the wall time is honest e2e
    with NO key ever crossing the relay — and (b) the on-device validation
    pass, each as its own JSON line (min over reps; one-sided jitter
    doctrine).  This is also the `make bench-smoke` target, tier-1-gated in
    `tests/test_device_resident.py`.
    """
    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.models.validate import _multiset

    dtype = np.dtype(cfg.job.key_dtype)
    data = gen_uniform(args.n, dtype=dtype, seed=0)
    sched = _make_device_scheduler(cfg)
    journal = _open_journal(args)
    handle = sched.sort(data, keep_on_device=True)  # warm sort program
    handle.validate_on_device()                     # warm validator
    sort_times, val_times = [], []
    rep = None
    try:
        for _ in range(args.reps):
            metrics = Metrics(journal=journal)
            t0 = time.perf_counter()
            handle = sched.sort(data, metrics=metrics, keep_on_device=True)
            sort_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            rep = handle.validate_on_device()
            val_times.append(time.perf_counter() - t0)
    finally:
        _write_journal(journal, args)
    in_sum = _multiset(data, len(data), dtype.itemsize)
    ok = bool(rep.sorted_ok and rep.records == len(data)
              and rep.checksum == in_sum)
    dt, dtv = float(min(sort_times)), float(min(val_times))
    for line in (
        {
            "metric": f"sort_e2e_device_resident_{dtype}_{args.n}_keys",
            "value": round(args.n / dt, 1),
            "unit": "keys/sec",
            "vs_baseline": round(args.n / dt / _REF_KEYS_PER_SEC, 2),
        },
        {
            "metric": f"device_validate_{dtype}_{args.n}_keys",
            "value": round(args.n / dtv, 1),
            "unit": "keys/sec",
            "validated_ok": ok,
        },
    ):
        print(json.dumps(line), flush=True)
    return 0 if ok else 1


def _bench_exchange_ab(args, cfg: SortConfig) -> int:
    """`dsort bench --exchange-ab`: the three-way exchange A/B on the local
    mesh — lax all_to_all vs lax ring vs the FUSED Pallas ring kernel.

    The `make bench-exchange-smoke` / `make bench-fused-smoke` targets
    (tier-1-gated like bench-smoke), and THE exchange harness — bench.py's
    cpu-mesh ladder shells out to this command so the A/B contract lives in
    one place: for a uniform int32, a zipf-skewed int64, and a TeraSort kv
    workload, sorts the same data through every schedule, asserts the
    outputs bit-identical, and emits per workload (a) the unchanged
    ring-vs-alltoall row with both throughputs and the measured per-sort
    ``bytes_on_wire`` of each schedule (the counter charges every attempt —
    an overflowed padded dispatch pays for its failed shipment too), and
    (b) a ``exchange_fused_vs_ring_*`` row whose structural axis is
    ``dispatches_per_exchange``: the lax ring issues P-1 ppermute
    collectives per exchange, the fused kernel exactly ONE launch
    (`ops.ring_kernel`).  On the CPU mesh the fused end-to-end figure is a
    dispatch-overhead comparison only — the comm/compute overlap the kernel
    exists for needs real ICI.
    """
    import jax

    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf
    from dsort_tpu.parallel.exchange import dispatches_per_exchange
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort

    mesh = local_device_mesh(cfg.mesh.num_workers)
    # Guard on the mesh ACTUALLY used (a NUM_WORKERS=1 config on an
    # 8-device host would otherwise silently benchmark alltoall against
    # itself — resolve_exchange forces ring back to alltoall at P=1).
    if mesh.shape["w"] < 2:
        raise SystemExit(
            "--exchange-ab needs a multi-worker mesh (the ring and the "
            "all_to_all are the same program on one worker); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 without "
            "NUM_WORKERS=1"
        )
    journal = _open_journal(args)
    tk, tv = gen_terasort(1 << 16, seed=3)
    cases = [
        (
            f"uniform_int32_{args.n}",
            gen_uniform(args.n, seed=0),
            None,
            JobConfig(local_kernel=cfg.job.local_kernel),
        ),
        (
            f"zipf_int64_{args.n}",
            gen_zipf(args.n, a=1.3, seed=4),
            None,
            JobConfig(key_dtype=np.int64, local_kernel=cfg.job.local_kernel),
        ),
        (
            "kv_65536_records",
            tk,
            tv,
            JobConfig(
                key_dtype=np.uint64, payload_bytes=tv.shape[1],
                local_kernel=cfg.job.local_kernel,
            ),
        ),
    ]
    ok_all = True
    try:
        for label, keys, payload, job in cases:
            ss = SampleSort(mesh, job)

            def run(exch, m=None):
                if payload is None:
                    return ss.sort(keys, metrics=m, exchange=exch)
                return ss.sort_kv(keys, payload, metrics=m, exchange=exch)

            def canonical(out):
                # Keys-only: the sorted array compares directly.  kv: keys
                # must be bit-identical AND the records the same multiset —
                # payload order among EQUAL keys is unspecified on both
                # schedules (unstable local sorts), so compare records in a
                # canonical (key, payload-bytes) order; this is what
                # catches a ring payload-permutation bug that ships sorted
                # keys over scrambled values.
                if payload is None:
                    return out
                k, v = out
                order = np.lexsort(
                    tuple(v[:, i] for i in range(v.shape[1])) + (k,)
                )
                return k, k[order].tobytes() + v[order].tobytes()

            results, stats = {}, {}
            for exch in ("alltoall", "ring", "fused"):
                run(exch)  # warm/compile
                times = []
                m = Metrics(journal=journal)
                for _ in range(args.reps):
                    t0 = time.perf_counter()
                    out = run(exch, m)
                    times.append(time.perf_counter() - t0)
                results[exch] = canonical(out)
                # Counters accumulated over the reps: report EVERYTHING
                # per-sort (each rep restarts from the policy capacity, so
                # retries divide evenly like the bytes).
                stats[exch] = {
                    "dt": float(min(times)),  # one-sided jitter doctrine
                    "bytes": m.counters.get("exchange_bytes_on_wire", 0)
                    // args.reps,
                    "retries": m.counters.get("capacity_retries", 0)
                    // args.reps,
                    "saved": m.counters.get("exchange_bytes_saved", 0)
                    // args.reps,
                    "launches": m.counters.get("fused_exchange_launches", 0)
                    // args.reps,
                }

            def same(a, b):
                if payload is None:
                    return bool(np.array_equal(results[a], results[b]))
                return bool(
                    np.array_equal(results[a][0], results[b][0])
                ) and results[a][1] == results[b][1]

            identical = same("alltoall", "ring")
            fused_identical = same("ring", "fused")
            ok_all = ok_all and identical and fused_identical
            n = len(keys)
            p = mesh.shape["w"]
            unit = "keys/sec" if payload is None else "rec/sec"
            print(json.dumps({
                "metric": f"exchange_ring_vs_alltoall_{label}",
                "value": round(n / stats["ring"]["dt"], 1),
                "unit": unit,
                "alltoall_keys_per_sec": round(
                    n / stats["alltoall"]["dt"], 1
                ),
                "speedup_vs_alltoall": round(
                    stats["alltoall"]["dt"] / stats["ring"]["dt"], 2
                ),
                "bytes_on_wire": stats["ring"]["bytes"],
                "bytes_on_wire_alltoall": stats["alltoall"]["bytes"],
                "bytes_saved": stats["ring"]["saved"],
                "capacity_retries_alltoall": stats["alltoall"]["retries"],
                "capacity_retries_ring": stats["ring"]["retries"],
                "bit_identical": identical,
            }), flush=True)
            print(json.dumps({
                "metric": f"exchange_fused_vs_ring_{label}",
                "value": round(n / stats["fused"]["dt"], 1),
                "unit": unit,
                "ring_keys_per_sec": round(n / stats["ring"]["dt"], 1),
                "speedup_vs_ring": round(
                    stats["ring"]["dt"] / stats["fused"]["dt"], 2
                ),
                "dispatches_per_exchange": dispatches_per_exchange(
                    "fused", p
                ),
                "dispatches_per_exchange_ring": dispatches_per_exchange(
                    "ring", p
                ),
                "fused_launches_per_sort": stats["fused"]["launches"],
                "bytes_on_wire": stats["fused"]["bytes"],
                "capacity_retries": stats["fused"]["retries"],
                "bit_identical": fused_identical,
            }), flush=True)
    finally:
        _write_journal(journal, args)
    return 0 if ok_all else 1


def _bench_coded_ab(args, cfg: SortConfig) -> int:
    """`dsort bench --coded-ab`: the coded-redundancy failure A/B.

    The `make coded-smoke` target (tier-1-gated) and THE acceptance
    harness for the coded plane (ARCHITECTURE §14): the SAME zipf workload
    through `SpmdScheduler` four ways — redundancy=1 vs 2, healthy vs one
    injected mid-ring device loss.  The uncoded faulted arm recovers by
    today's re-form-and-re-run (the measured ~2.4x hit of
    ``config5_zipf_1M_injected_failure``); the coded faulted arm recovers
    by a LOCAL merge of replica slots — counter-asserted: exactly one
    ``coded_recoveries`` per faulted sort, zero re-dispatch.  Every arm's
    output must be bit-identical to ``np.sort``; the rows report
    ``throughput_under_failure_ratio`` (coded faulted vs uncoded healthy)
    next to the re-run baseline's ratio and the healthy-path replica
    overhead (``replica_overhead_frac`` — the availability premium: ~r x
    exchange wire bytes).  Healthy arms warm once and report min-of-reps;
    each FAULTED rep runs on a FRESH scheduler (healthy warm pass off the
    clock) so the timed run pays its true recovery cost — for the re-run
    arm that is the re-dispatch PLUS the re-formed mesh's recompile
    (exactly what ``config5_zipf_1M_injected_failure`` measured as the
    2.4x hit, and exactly what the coded arm structurally avoids).
    """
    import jax

    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_zipf
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    devices = jax.devices()
    if len(devices) < 2:
        raise SystemExit(
            "--coded-ab needs a multi-device mesh (there is no replica "
            "holder on one device); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    # The injected victim must exist on THIS mesh, whatever its size —
    # device 3 on the canonical 8-device mesh, the last device otherwise
    # (its r=2 replica holder is device 0, wrapping the ring).
    victim = min(3, len(devices) - 1)
    journal = _open_journal(args)
    data = gen_zipf(args.n, a=1.3, seed=5)
    expect = np.sort(data)
    n = len(data)

    def make_sched(red: int):
        inj = FaultInjector()
        return inj, SpmdScheduler(
            devices=devices,
            job=JobConfig(
                settle_delay_s=0.01, exchange="ring", redundancy=red,
                key_dtype=np.int64, local_kernel=cfg.job.local_kernel,
            ),
            injector=inj,
        )

    def run_arm(red: int, fault: bool):
        times = []
        m = Metrics(journal=journal)
        out = None
        if not fault:
            _, sched = make_sched(red)
            sched.sort(data)  # warm the healthy P-device programs
            for _ in range(args.reps):
                t0 = time.perf_counter()
                out = sched.sort(data, metrics=m)
                times.append(time.perf_counter() - t0)
            return float(min(times)), m, out
        for _ in range(args.reps):
            # A fresh scheduler per faulted rep: the timed sort pays its
            # TRUE recovery cost — the re-run arm's re-dispatch includes
            # the re-formed mesh's recompile (the config5 semantics); the
            # coded arm never re-dispatches, so it pays only the replica
            # fetch + local merge.
            inj, sched = make_sched(red)
            sched.sort(data)  # healthy warm pass, off the clock
            inj.fail_once(victim, "ring")
            t0 = time.perf_counter()
            out = sched.sort(data, metrics=m)
            times.append(time.perf_counter() - t0)
        return float(min(times)), m, out

    try:
        arms = {}
        ok_all = True
        for red, fault in ((1, False), (2, False), (1, True), (2, True)):
            dt, m, out = run_arm(red, fault)
            identical = bool(np.array_equal(out, expect))
            ok_all = ok_all and identical
            arms[(red, fault)] = {
                "dt": dt,
                "identical": identical,
                "coded_recoveries": m.counters.get("coded_recoveries", 0)
                // args.reps,
                "recovered_keys": m.counters.get("coded_recovered_keys", 0)
                // args.reps,
                "replica_bytes": m.counters.get("coded_replica_bytes", 0)
                // args.reps,
                "mesh_reforms": m.counters.get("mesh_reforms", 0)
                // args.reps,
            }
        h1, h2 = arms[(1, False)], arms[(2, False)]
        f1, f2 = arms[(1, True)], arms[(2, True)]
        # Contract: the coded faulted arm recovers locally (one coded
        # recovery per sort, zero re-sorted keys) — not just fast.
        ok_all = ok_all and f2["coded_recoveries"] == 1
        print(json.dumps({
            "metric": f"coded_redundancy_healthy_zipf_{args.n}",
            "value": round(n / h2["dt"], 1),
            "unit": "keys/sec",
            "baseline_keys_per_sec": round(n / h1["dt"], 1),
            "replica_overhead_frac": round(
                max(h2["dt"] - h1["dt"], 0.0) / h1["dt"], 4
            ),
            "redundancy": 2,
            "coded_replica_bytes": h2["replica_bytes"],
            "bit_identical": h1["identical"] and h2["identical"],
        }), flush=True)
        print(json.dumps({
            "metric": f"coded_redundancy_failure_zipf_{args.n}",
            "value": round(n / f2["dt"], 1),
            "unit": "keys/sec",
            "baseline_keys_per_sec": round(n / h1["dt"], 1),
            "rerun_keys_per_sec": round(n / f1["dt"], 1),
            "throughput_under_failure_ratio": round(h1["dt"] / f2["dt"], 3),
            "rerun_failure_ratio": round(h1["dt"] / f1["dt"], 3),
            "redundancy": 2,
            "coded_recoveries": f2["coded_recoveries"],
            "recovered_keys": f2["recovered_keys"],
            "mesh_reforms": f2["mesh_reforms"],
            "includes_reform_and_recompile": True,
            "bit_identical": all(a["identical"] for a in arms.values()),
        }), flush=True)
    finally:
        _write_journal(journal, args)
    return 0 if ok_all else 1


def _bench_coded_v2_ab(args, cfg: SortConfig) -> int:
    """`dsort bench --coded-v2-ab`: the coded-exchange v2 acceptance A/B.

    The `make coded-v2-smoke` target (tier-1-gated) and THE acceptance
    harness for the v2 parity plane + straggler serving (ARCHITECTURE
    §18): the §14 zipf workload through `SpmdScheduler` at r=2,
    replicate vs parity — equal single-loss survivability — plus the
    injected-straggler drill.  Three rows, all gated (ok -> exit 0):

    - ``coded_v2_parity_premium``: healthy-path wire premium.  Parity
      must ship < 0.75x replicate's measured ``coded_replica_bytes`` on
      the same plan (one XOR slot vs a full replica per range).
    - ``coded_v2_parity_failure``: one injected mid-ring loss per mode.
      BOTH modes must recover locally — exactly one coded recovery per
      faulted sort, zero re-sorted keys (the parity arm SOLVES the dead
      range from its XOR slot; faulted reps run on a fresh scheduler
      with the healthy warm pass off the clock, the §14 semantics).
    - ``coded_v2_straggler``: `FaultInjector.slow` names a live-but-slow
      owner; the coded plane races owner fetch vs reconstruction and
      the p99 sort completion with serving ON must beat the
      wait-on-owner baseline — measured from the SAME reps as the
      losing owner leg's own completion time (`join_stragglers` drain),
      which pays the injected delay the serve dodged.  Exactly one
      ``coded_straggler_serves`` per rep, no failure, no mesh re-form.

    Every arm's output must be bit-identical to ``np.sort``.
    """
    import math

    import jax

    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_zipf
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    devices = jax.devices()
    if len(devices) < 2:
        raise SystemExit(
            "--coded-v2-ab needs a multi-device mesh (there is no parity "
            "holder on one device); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    victim = min(3, len(devices) - 1)  # replica/parity holder wraps the ring
    slow_s = 0.5  # the injected straggler's extra owner-fetch latency
    journal = _open_journal(args)
    data = gen_zipf(args.n, a=1.3, seed=5)
    expect = np.sort(data)
    n = len(data)

    def make_sched(red: int, mode: str):
        inj = FaultInjector()
        return inj, SpmdScheduler(
            devices=devices,
            job=JobConfig(
                settle_delay_s=0.01, exchange="ring", redundancy=red,
                redundancy_mode=mode, key_dtype=np.int64,
                local_kernel=cfg.job.local_kernel,
            ),
            injector=inj,
        )

    def drain(sched) -> None:
        for ss in sched._sorters.values():
            ss.join_stragglers()

    def p99(times: list) -> float:
        ts = sorted(times)
        return float(ts[max(0, math.ceil(0.99 * len(ts)) - 1)])

    def run_arm(red: int, mode: str, shape: str):
        """shape: 'healthy' | 'loss' | 'slow'.  Returns (times, owner
        times, per-arm metrics, last output): faulted/slow reps each run
        on a FRESH scheduler with a healthy warm pass off the clock, so
        the timed sort pays its true recovery/serve cost."""
        times, owner_times = [], []
        m = Metrics(journal=journal)
        out = None
        if shape == "healthy":
            _, sched = make_sched(red, mode)
            sched.sort(data)  # warm the healthy P-device programs
            for _ in range(args.reps):
                t0 = time.perf_counter()
                out = sched.sort(data, metrics=m)
                times.append(time.perf_counter() - t0)
            return times, owner_times, m, out
        for _ in range(args.reps):
            inj, sched = make_sched(red, mode)
            sched.sort(data)  # healthy warm pass, off the clock
            if shape == "loss":
                inj.fail_once(victim, "ring")
            else:
                inj.slow(victim, slow_s)
            t0 = time.perf_counter()
            out = sched.sort(data, metrics=m)
            times.append(time.perf_counter() - t0)
            # The losing owner leg is still sleeping out the injected
            # delay; its completion time IS the wait-on-owner baseline
            # for this rep (what the sort would have cost without the
            # race).  Drain it before the next rep so claims stay 1/rep.
            drain(sched)
            owner_times.append(time.perf_counter() - t0)
        return times, owner_times, m, out

    try:
        arms = {}
        ok_all = True
        for red, mode, shape in (
            (1, "replicate", "healthy"),
            (2, "replicate", "healthy"),
            (2, "parity", "healthy"),
            (2, "replicate", "loss"),
            (2, "parity", "loss"),
            (2, "parity", "slow"),
        ):
            times, owner_times, m, out = run_arm(red, mode, shape)
            identical = bool(np.array_equal(out, expect))
            ok_all = ok_all and identical
            arms[(red, mode, shape)] = {
                "dt": float(min(times)),
                "p99": p99(times),
                "p99_owner": p99(owner_times) if owner_times else 0.0,
                "identical": identical,
                "coded_recoveries": m.counters.get("coded_recoveries", 0)
                // args.reps,
                "recovered_keys": m.counters.get("coded_recovered_keys", 0)
                // args.reps,
                "replica_bytes": m.counters.get("coded_replica_bytes", 0)
                // args.reps,
                "straggler_serves": m.counters.get(
                    "coded_straggler_serves", 0
                ) // args.reps,
                "resort_keys": m.counters.get("shuffle_resort_keys", 0),
                "mesh_reforms": m.counters.get("mesh_reforms", 0)
                // args.reps,
            }
        h0 = arms[(1, "replicate", "healthy")]
        hr, hp = arms[(2, "replicate", "healthy")], arms[(2, "parity", "healthy")]
        fr, fp = arms[(2, "replicate", "loss")], arms[(2, "parity", "loss")]
        sl = arms[(2, "parity", "slow")]
        premium = hp["replica_bytes"] / max(hr["replica_bytes"], 1)
        # Gate 1: parity's availability premium undercuts replication.
        ok_all = ok_all and hp["replica_bytes"] > 0 and premium < 0.75
        # Gate 2: both modes recover the injected loss LOCALLY.
        for f in (fr, fp):
            ok_all = (
                ok_all and f["coded_recoveries"] == 1
                and f["resort_keys"] == 0
            )
        # Gate 3: serving beats waiting on the slow owner, exactly once,
        # with no failure machinery involved.
        ok_all = (
            ok_all and sl["straggler_serves"] == 1
            and sl["p99"] < sl["p99_owner"]
            and sl["mesh_reforms"] == 0
        )
        print(json.dumps({
            "metric": f"coded_v2_parity_premium_zipf_{args.n}",
            "value": round(n / hp["dt"], 1),
            "unit": "keys/sec",
            "baseline_keys_per_sec": round(n / h0["dt"], 1),
            "replicate_keys_per_sec": round(n / hr["dt"], 1),
            "replica_overhead_frac": round(
                max(hp["dt"] - h0["dt"], 0.0) / h0["dt"], 4
            ),
            "redundancy": 2,
            "redundancy_mode": "parity",
            "coded_replica_bytes": hp["replica_bytes"],
            "replicate_replica_bytes": hr["replica_bytes"],
            "premium_ratio": round(premium, 4),
            "bit_identical": hp["identical"] and hr["identical"],
        }), flush=True)
        print(json.dumps({
            "metric": f"coded_v2_parity_failure_zipf_{args.n}",
            "value": round(n / fp["dt"], 1),
            "unit": "keys/sec",
            "baseline_keys_per_sec": round(n / h0["dt"], 1),
            "replicate_keys_per_sec": round(n / fr["dt"], 1),
            "throughput_under_failure_ratio": round(h0["dt"] / fp["dt"], 3),
            "redundancy": 2,
            "redundancy_mode": "parity",
            "coded_recoveries": fp["coded_recoveries"],
            "recovered_keys": fp["recovered_keys"],
            "mesh_reforms": fp["mesh_reforms"],
            "includes_reform_and_recompile": True,
            "bit_identical": fp["identical"] and fr["identical"],
        }), flush=True)
        print(json.dumps({
            "metric": f"coded_v2_straggler_zipf_{args.n}",
            "value": round(n / sl["p99"], 1),
            "unit": "keys/sec",
            "baseline_keys_per_sec": round(n / h0["dt"], 1),
            "p99_serve_s": round(sl["p99"], 4),
            "p99_owner_s": round(sl["p99_owner"], 4),
            "speedup_vs_wait": round(sl["p99_owner"] / sl["p99"], 2),
            "slow_s": slow_s,
            "redundancy": 2,
            "redundancy_mode": "parity",
            "straggler_serves": sl["straggler_serves"],
            "mesh_reforms": sl["mesh_reforms"],
            "bit_identical": sl["identical"],
        }), flush=True)
    finally:
        _write_journal(journal, args)
    return 0 if ok_all else 1


def _bench_hier_ab(args, cfg: SortConfig) -> int:
    """`dsort bench --hier-ab`: the pod-scale two-level exchange A/B.

    The `make hier-smoke` target (tier-1-gated) and THE acceptance harness
    for the hierarchical exchange plane (ARCHITECTURE §17): one zipf
    workload sorted flat-ring and two-level at every simulated ``H x D``
    topology the local mesh divides into, then the fault drills.  Gates
    (ok -> exit 0):

    - every arm's output bit-identical to ``np.sort`` (the schedule may
      only change HOW keys move, never WHAT comes back);
    - at every topology the journaled ``dcn_bytes_on_wire`` is LESS than
      what the flat ring would have pushed across the same host boundary
      for the same measured histogram (``ring_dcn_bytes``; the
      ``dcn_bytes_saved`` counter is exactly the difference) — the
      tentpole claim, measured, not asserted;
    - the DEVICE-loss drill re-forms within the host: losing devices of
      one host mid-exchange keeps the ``H``-host grouping (journaled
      ``hier_reform`` with ``hosts_before == hosts_after``) and returns
      bit-identical output;
    - the HOST-loss drill re-plans: losing ALL of one host's devices
      mid-phase-two re-forms the survivors under the largest divisor the
      mesh still supports (``hier_reform`` with ``hosts_after <
      hosts_before``) and returns bit-identical output.

    One JSON row per topology (throughputs + the DCN/intra wire split)
    plus one row per drill.
    """
    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_zipf
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler
    from dsort_tpu.utils.events import EventLog

    mesh = local_device_mesh(cfg.mesh.num_workers)
    p = int(mesh.shape["w"])
    if p < 4:
        raise SystemExit(
            "--hier-ab needs >= 4 devices (two simulated hosts of two "
            "devices is the smallest two-level topology); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    journal = _open_journal(args) or EventLog()
    data = gen_zipf(args.n, a=1.3, seed=6)
    expect = np.sort(data)
    n = len(data)
    # Every >=2-host grouping with >=2 devices per host the mesh divides
    # into — on the canonical 8-device mesh: 2x4 and 4x2.
    topologies = [h for h in (2, 4, 8) if h < p and p % h == 0 and p // h >= 2]
    job_kw = dict(key_dtype=np.int64, local_kernel=cfg.job.local_kernel)
    ok_all = True
    try:
        ss_ring = SampleSort(mesh, JobConfig(exchange="ring", **job_kw))
        ss_ring.sort(data)  # warm/compile
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            ring_out = ss_ring.sort(data)
            times.append(time.perf_counter() - t0)
        ring_dt = float(min(times))
        for hosts in topologies:
            ss = SampleSort(
                mesh, JobConfig(exchange="hier", hier_hosts=hosts, **job_kw)
            )
            ss.sort(data)  # warm/compile
            m = Metrics(journal=journal)
            times = []
            for _ in range(args.reps):
                t0 = time.perf_counter()
                out = ss.sort(data, metrics=m)
                times.append(time.perf_counter() - t0)
            dt = float(min(times))
            identical = bool(np.array_equal(out, expect)) and bool(
                np.array_equal(ring_out, expect)
            )
            dcn = m.counters.get("dcn_bytes_on_wire", 0) // args.reps
            intra = m.counters.get("intra_host_bytes_on_wire", 0) // args.reps
            saved = m.counters.get("dcn_bytes_saved", 0) // args.reps
            ring_dcn = dcn + saved  # the flat baseline, same histogram
            reduced = saved > 0
            ok = identical and reduced
            ok_all = ok_all and ok
            print(json.dumps({
                "metric": f"hier_exchange_zipf_{args.n}_h{hosts}",
                "value": round(n / dt, 1),
                "unit": "keys/sec",
                "hosts": hosts,
                "dev_per_host": p // hosts,
                "ring_keys_per_sec": round(n / ring_dt, 1),
                "dcn_bytes": int(dcn),
                "ring_dcn_bytes": int(ring_dcn),
                "dcn_reduction_frac": round(saved / ring_dcn, 4)
                if ring_dcn else 0.0,
                "intra_host_bytes": int(intra),
                "hier_exchanges": m.counters.get("hier_exchanges", 0)
                // args.reps,
                "bit_identical": identical,
            }), flush=True)

        # The fault drills: the hook fires between the (H, H) plan and the
        # exchange dispatch (the schedule is sized, the legs are "in
        # flight"), so a tripped loss invalidates the planned exchange and
        # the survivors re-plan — the two-level fault contract, measured.
        def drill(hosts: int, victims: list[int]):
            inj = FaultInjector()
            sched = SpmdScheduler(
                devices=list(mesh.devices.flat),
                job=JobConfig(
                    settle_delay_s=0.01, exchange="hier", hier_hosts=hosts,
                    **job_kw,
                ),
                injector=inj,
            )
            sched.sort(data)  # healthy warm pass, off the clock
            for w in victims:
                inj.fail_once(w, "ring")
            m = Metrics(journal=journal)
            t0 = time.perf_counter()
            out = sched.sort(data, metrics=m)
            dt = time.perf_counter() - t0
            reforms = [
                e for e in journal.events()
                if e.type == "hier_reform"
            ][-1:]
            rf = reforms[0].fields if reforms else {}
            identical = bool(np.array_equal(out, expect))
            return dt, m, rf, identical

        # Device loss, H=2: losing devices of host 0 (never the whole
        # host) re-forms WITHIN the host — the 2-host grouping survives.
        # Victims are chosen so the survivor count still divides by 2 (the
        # 1-D simulation has no fixed per-host slot map, so an odd
        # survivor count would force a downgrade a real pod's re-formed
        # host group would not).
        dev_victims = [1] if (p - 1) % 2 == 0 else [1, 2]
        dt_dev, m_dev, rf_dev, id_dev = drill(2, dev_victims)
        ok_dev = (
            id_dev and rf_dev.get("hosts_before") == 2
            and rf_dev.get("hosts_after") == 2
            and not rf_dev.get("downgraded")
        )
        ok_all = ok_all and ok_dev
        print(json.dumps({
            "metric": f"hier_device_loss_drill_zipf_{args.n}",
            "value": round(n / dt_dev, 1),
            "unit": "keys/sec",
            "hosts_before": rf_dev.get("hosts_before"),
            "hosts_after": rf_dev.get("hosts_after"),
            "downgraded": rf_dev.get("downgraded"),
            "survivors": rf_dev.get("survivors"),
            "mesh_reforms": m_dev.counters.get("mesh_reforms", 0),
            "bit_identical": id_dev,
        }), flush=True)
        # Host loss, H=4 (when the mesh supports it): ALL of host 1's
        # devices die mid-phase-two; the survivors no longer divide by 4,
        # so the re-plan lands on the largest divisor they do support.
        if 4 in topologies:
            dh = p // 4
            host_victims = list(range(dh, 2 * dh))
            dt_host, m_host, rf_host, id_host = drill(4, host_victims)
            ok_host = (
                id_host and rf_host.get("hosts_before") == 4
                and 2 <= int(rf_host.get("hosts_after") or 0) < 4
                and not rf_host.get("downgraded")
            )
            ok_all = ok_all and ok_host
            print(json.dumps({
                "metric": f"hier_host_loss_drill_zipf_{args.n}",
                "value": round(n / dt_host, 1),
                "unit": "keys/sec",
                "hosts_before": rf_host.get("hosts_before"),
                "hosts_after": rf_host.get("hosts_after"),
                "downgraded": rf_host.get("downgraded"),
                "survivors": rf_host.get("survivors"),
                "mesh_reforms": m_host.counters.get("mesh_reforms", 0),
                "bit_identical": id_host,
            }), flush=True)
    finally:
        if getattr(args, "journal", None):
            journal.flush_jsonl(args.journal)
    return 0 if ok_all else 1


def _bench_autotune_ab(args, cfg: SortConfig) -> int:
    """`dsort bench --autotune-ab`: does the planner pay for itself?

    The `make autotune-smoke` target (tier-1-gated) and THE acceptance
    harness for the planner plane (ARCHITECTURE §15): a zipf-skewed int64
    workload and a uniform int32 workload, each sorted three ways on the
    local mesh — exchange hand-set to alltoall, hand-set to ring, and a
    third arm with ``autotune=True`` and the exchange knob genuinely
    unset, so the planner's measured skew probe picks the schedule per
    dispatch and journals a ``plan_decision``.  Gates (ok -> exit 0):

    - every arm's output bit-identical (the planner may only change HOW
      keys move, never WHAT comes back);
    - the planner picks ring on the zipf workload and alltoall on the
      uniform one (the measured ``max_mean_ratio`` vs
      ``SKEW_RING_THRESHOLD`` contract — the zipf head lands ~P x the
      mean bucket, uniform sits at ~1.0);
    - the autotune arm lands within 0.95x of the BEST hand-set arm
      (probe overhead must not eat the win).  Below 1M keys the
      throughput gate relaxes to the structural checks only, the same
      doctrine as ``--analyze-smoke``: at smoke sizes the fixed per-sort
      dispatch cost drowns the schedule delta and the probe share, so
      tiny runs check the plane end-to-end, the 1M ladder row checks the
      number.

    One JSON row per workload with both hand-set throughputs, the
    autotune throughput, the chosen schedule, and the journaled
    plan_decision count.
    """
    import dataclasses

    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_uniform, gen_zipf
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.utils.events import EventLog

    mesh = local_device_mesh(cfg.mesh.num_workers)
    if mesh.shape["w"] < 2:
        raise SystemExit(
            "--autotune-ab needs a multi-worker mesh (every exchange "
            "schedule is the same program on one worker); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 without "
            "NUM_WORKERS=1"
        )
    journal = _open_journal(args) or EventLog()
    cases = [
        (
            f"zipf_int64_{args.n}",
            gen_zipf(args.n, a=1.3, seed=4),
            JobConfig(key_dtype=np.int64, local_kernel=cfg.job.local_kernel),
            # No TPU on the cpu mesh: the skewed pick is the lax ring
            # (fused is the TPU-gated upgrade of the same measured plan).
            "ring",
        ),
        (
            f"uniform_int32_{args.n}",
            gen_uniform(args.n, seed=0),
            JobConfig(local_kernel=cfg.job.local_kernel),
            "alltoall",
        ),
    ]
    ok_all = True
    try:
        for label, keys, job, expected in cases:
            ss_hand = SampleSort(mesh, job)
            arms = {}
            for exch in ("alltoall", "ring"):
                ss_hand.sort(keys, exchange=exch)  # warm/compile
                times = []
                for _ in range(args.reps):
                    t0 = time.perf_counter()
                    out = ss_hand.sort(keys, exchange=exch)
                    times.append(time.perf_counter() - t0)
                arms[exch] = {"dt": float(min(times)), "out": out}
            # The autotune arm: exchange genuinely unset — the planner's
            # per-dispatch skew probe decides, and every timed rep
            # journals its plan_decision with the measured inputs.
            ss_auto = SampleSort(mesh, dataclasses.replace(job, autotune=True))
            ss_auto.sort(keys)  # warm/compile (probe runs, unjournaled)
            start = len(journal)
            m = Metrics(journal=journal)
            times = []
            for _ in range(args.reps):
                t0 = time.perf_counter()
                auto_out = ss_auto.sort(keys, metrics=m)
                times.append(time.perf_counter() - t0)
            auto_dt = float(min(times))
            plans = [
                e for e in journal.events()[start:]
                if e.type == "plan_decision"
                and e.fields.get("policy") == "exchange"
            ]
            chosen = plans[-1].fields.get("chosen") if plans else None
            identical = bool(
                np.array_equal(auto_out, arms["alltoall"]["out"])
            ) and bool(np.array_equal(auto_out, arms["ring"]["out"]))
            best_arm = min(arms, key=lambda a: arms[a]["dt"])
            best_dt = arms[best_arm]["dt"]
            vs_best = best_dt / auto_dt if auto_dt > 0 else 0.0
            # The 0.95x floor binds at ladder size (1M+); smoke sizes are
            # structural-only (see the docstring).
            fast_enough = vs_best >= 0.95 or args.n < (1 << 20)
            ok = (
                identical and chosen == expected and len(plans) == args.reps
                and fast_enough
            )
            ok_all = ok_all and ok
            n = len(keys)
            print(json.dumps({
                "metric": f"autotune_ab_{label}",
                "value": round(n / auto_dt, 1),
                "unit": "keys/sec",
                "chosen_exchange": chosen,
                "expected_exchange": expected,
                "best_arm": best_arm,
                "best_keys_per_sec": round(n / best_dt, 1),
                "alltoall_keys_per_sec": round(n / arms["alltoall"]["dt"], 1),
                "ring_keys_per_sec": round(n / arms["ring"]["dt"], 1),
                "autotune_vs_best": round(vs_best, 3),
                "plan_decisions": len(plans),
                "bit_identical": identical,
            }), flush=True)
    finally:
        if getattr(args, "journal", None):
            journal.flush_jsonl(args.journal)
    return 0 if ok_all else 1


def _queue_fairness(events, tenants) -> tuple[float, float]:
    """``(p95_wait_s, fairness_p95_ratio)`` from journaled ``job_dequeued``
    records — THE fairness computation both serving benchmarks share.
    Big jobs are excluded from the per-tenant comparison: a large job's
    long wait is its deficit-round-robin cost paying off (it must
    accumulate the whole mesh), not a tenant being starved."""
    waits: dict[str, list[float]] = {}
    all_waits: list[float] = []
    for e in events:
        if e.type == "job_dequeued":
            w = float(e.fields.get("wait_s", 0.0))
            all_waits.append(w)
            if not e.fields.get("big"):
                waits.setdefault(e.fields.get("tenant", "?"), []).append(w)
    p95 = float(np.percentile(all_waits, 95)) if all_waits else 0.0
    tenant_p95 = {
        t: float(np.percentile(ws, 95))
        for t, ws in waits.items() if t in tenants and ws
    }
    fairness = (
        max(tenant_p95.values()) / max(min(tenant_p95.values()), 1e-9)
        if len(tenant_p95) > 1 else 1.0
    )
    return p95, fairness


def _bench_serve_mixed(args, cfg: SortConfig) -> int:
    """`dsort bench --serve-mixed`: the multi-tenant serving benchmark.

    The `make serve-smoke` target and THE acceptance harness for the
    serving layer (ARCHITECTURE §8): a mixed workload — 4 small jobs from
    each of 3 tenants (two repeat sizes, so the compiled-variant cache can
    prove reuse) plus one large job — submitted concurrently through the
    real admission queue onto the packed mesh.  Asserts every output
    bit-identical to ``np.sort`` of its input, then emits ONE JSON line:
    jobs/s over the mixed workload, p95 queue wait from the journal's
    ``job_dequeued`` records, per-tenant p95 fairness ratio, compiled-
    variant cache hit rate, and the packed-vs-serial small-job speedup
    (the same jobs through a single-slice service).
    """
    import dataclasses

    import jax

    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.obs import Telemetry
    from dsort_tpu.serve import SortService
    from dsort_tpu.utils.events import EventLog

    devs = jax.devices()
    n_devs = cfg.mesh.num_workers or len(devs)
    if n_devs < 2:
        raise SystemExit(
            "--serve-mixed needs a multi-device mesh (packing small jobs "
            "onto sub-slices of one device is serial dispatch by another "
            "name); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    devs = devs[:n_devs]
    n_small = max(min(args.n, 1 << 19), 1 << 10)
    n_large = 1 << 20  # >= FUSED_SMALL_JOB_MAX: routes to the full mesh
    tenants = ("acme", "blue", "coral")
    rng = np.random.default_rng(0)
    # Two repeat sizes per tenant: repeat-size jobs are where the variant
    # cache must show ≥ 50% hits.
    small_jobs = []
    for j in range(4):
        for t in tenants:
            n = n_small if j % 2 == 0 else max(n_small // 2, 1 << 9)
            small_jobs.append(
                (t, rng.integers(0, 1 << 30, n).astype(np.int32))
            )
    large = rng.integers(0, 1 << 30, n_large).astype(np.int32)
    serve_cfg = dataclasses.replace(
        cfg.serve,
        max_queue_depth=max(cfg.serve.max_queue_depth, len(small_jobs) + 4),
        max_tenant_inflight=max(
            cfg.serve.max_tenant_inflight, len(small_jobs) + 2
        ),
    )
    journal = _open_journal(args) or EventLog()
    tel = Telemetry()

    def run_window(svc, jobs, with_large: bool) -> tuple[float, bool]:
        t0 = time.perf_counter()
        tickets = [svc.submit(d, tenant=t)[1] for t, d in jobs]
        if with_large:
            tickets.append(svc.submit(large, tenant="acme")[1])
        ok = True
        for (t, d), ticket in zip(jobs + ([("acme", large)] if with_large else []), tickets):
            out = ticket.result(timeout=600)
            ok = ok and bool(np.array_equal(out, np.sort(d)))
        return time.perf_counter() - t0, ok

    # Serial baseline: the same small jobs through a ONE-slice service
    # (slice_devices = mesh size), prewarmed like the packed one — the
    # delta is pure packing, not compiles.
    sizes = sorted({len(d) for _, d in small_jobs})
    serial = SortService(
        devices=devs,
        job=cfg.job,
        serve=dataclasses.replace(serve_cfg, slice_devices=n_devs),
    )
    serial.prewarm(sizes=sizes)
    dt_serial, ok_serial = run_window(serial, small_jobs, with_large=False)
    serial.shutdown()

    svc = SortService(
        devices=devs, job=cfg.job, serve=serve_cfg, telemetry=tel,
        journal=journal,
    )
    prewarmed = svc.prewarm(sizes=sizes)
    svc._sched.sort(large)  # warm the full-mesh SPMD program once
    dt_packed, ok_packed = run_window(svc, small_jobs, with_large=False)
    mixed_start = len(journal)
    dt_mixed, ok_mixed = run_window(svc, small_jobs, with_large=True)
    stats = svc.stats()
    hit_rate = svc.variants.hit_rate()
    svc.shutdown()
    try:
        if getattr(args, "journal", None):
            journal.flush_jsonl(args.journal)
    except OSError as e:
        log.warning("serve-mixed journal write failed: %s", e)
    p95, fairness = _queue_fairness(journal.events()[mixed_start:], tenants)
    ok = ok_serial and ok_packed and ok_mixed
    jobs_total = len(small_jobs) + 1
    print(json.dumps({
        "metric": "service_mixed_workload",
        "value": round(jobs_total / dt_mixed, 2),
        "unit": "jobs/sec",
        "jobs": jobs_total,
        "tenants": len(tenants),
        "p95_queue_wait_ms": round(p95 * 1e3, 2),
        "fairness_p95_ratio": round(fairness, 2),
        "cache_hit_rate": round(hit_rate, 3),
        "prewarmed": prewarmed,
        "speedup_vs_serial": round(dt_serial / dt_packed, 2),
        "bit_identical": ok,
        "slices": stats["slices"],
    }), flush=True)
    return 0 if ok else 1


def _bench_external_wave(args, cfg: SortConfig) -> int:
    """`dsort bench --external-wave`: the out-of-core wave pipeline bench.

    The `make external-smoke` target and THE acceptance harness for
    ROADMAP item 2 (ARCHITECTURE §10).  Sorts a binary key file ``W``
    times larger than the per-wave device budget (``over_hbm_factor`` = W,
    default 8) through the wave pipeline on the local mesh and emits JSON
    rows:

    - ``external_wave_sort_uniform_*``: keys/s with the overlap ON,
      bit-identical to ``np.sort`` of the same data, plus the same-data
      no-overlap A/B (``overlap_speedup`` = sequential / pipelined — the
      measured value of overlapping wave k's exchange with wave k-1's
      spill);
    - ``external_wave_fault_drill_*``: the same job with a device loss
      injected inside a middle wave's ring — repaired at run granularity
      in flight; ``resume_fraction`` (re-sorted runs / total runs) must
      stay ≤ 1/num_waves + one wave's slack, and the output is still
      bit-identical.
    """
    import tempfile

    import jax

    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.models.wave_sort import ExternalWaveSort
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.scheduler.fault import WorkerFailure

    mesh = local_device_mesh(cfg.mesh.num_workers)
    p = int(mesh.shape["w"])
    if p < 2:
        raise SystemExit(
            "--external-wave needs a multi-device mesh (the wave exchange "
            "is the pipeline under test); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    n = max(args.n, 1 << 14)
    num_waves = 8  # the dataset is 8x the per-wave device budget
    wave_elems = -(-n // num_waves)
    journal = _open_journal(args)
    rows: list[dict] = []

    with tempfile.TemporaryDirectory() as td:
        in_path = os.path.join(td, "in.bin")
        data = gen_uniform(n, dtype=np.int32, seed=3)
        data.tofile(in_path)
        mm = np.memmap(in_path, dtype=np.int32, mode="r")
        expect = np.sort(data)

        def run(tag, overlap, fault_wave=None, reps=1):
            # ONE sorter per mode: its compiled plan/ring programs persist
            # across reps (instance-level caches), so min-of-reps times the
            # pipeline, not the compiler.
            s = ExternalWaveSort(
                mesh, wave_elems=wave_elems,
                spill_dir=os.path.join(td, "spill"),
                job_id=f"bench_{tag}", resume=False, overlap=overlap,
            )
            if fault_wave is not None:
                calls = {"n": 0}

                def hook():
                    calls["n"] += 1
                    if calls["n"] == fault_wave + 1:
                        raise WorkerFailure(
                            "injected mid-ring device loss (bench drill)"
                        )

                s.fault_hook = hook
            best, counters, all_ok = None, None, True
            for _ in range(reps):
                m = Metrics(journal=journal)
                out = np.empty(n, np.int32)
                t0 = time.perf_counter()
                s.sort(mm, out=out, metrics=m)
                dt = time.perf_counter() - t0
                # EVERY rep must be bit-identical — a wrong fast rep must
                # fail the row, not hide behind a correct slower one.
                all_ok = all_ok and bool(np.array_equal(out, expect))
                if best is None or dt < best:
                    best = dt
                counters = dict(m.counters)
            return best, all_ok, counters

        # Warm the shared-input page cache + one compile set off the clock.
        run("warm", overlap=True)
        dt_seq, ok_seq, _ = run("seq", overlap=False, reps=args.reps)
        dt_pipe, ok_pipe, c_pipe = run("pipe", overlap=True, reps=args.reps)
        total_runs = num_waves * p
        rows.append({
            "metric": f"external_wave_sort_uniform_{_nlabel(n)}",
            "value": round(n / dt_pipe, 1),
            "unit": "keys/sec",
            "bit_identical": bool(ok_pipe and ok_seq),
            "over_hbm_factor": num_waves,
            "num_waves": num_waves,
            "overlap_speedup": round(dt_seq / dt_pipe, 3),
            "resume_fraction": 0.0,
            "bytes_on_wire": int(c_pipe.get("exchange_bytes_on_wire", 0)),
            "exchange": "ring",
        })
        dt_f, ok_f, c_f = run("fault", overlap=True, fault_wave=num_waves // 2)
        resorted = int(c_f.get("wave_runs_resorted", 0))
        frac = resorted / total_runs
        rows.append({
            "metric": f"external_wave_fault_drill_{_nlabel(n)}",
            "value": round(n / dt_f, 1),
            "unit": "keys/sec",
            "bit_identical": bool(ok_f),
            "over_hbm_factor": num_waves,
            "num_waves": num_waves,
            "runs_resorted": resorted,
            "resume_fraction": round(frac, 4),
            "exchange": "ring",
        })
        ok = (
            ok_seq and ok_pipe and ok_f
            and 0 < resorted
            and frac <= 1.0 / num_waves + 1.0 / total_runs
        )
    for row in rows:
        print(json.dumps(row), flush=True)
    if journal is not None:
        journal.flush_jsonl(args.journal)
    return 0 if ok else 1


def _nlabel(n: int) -> str:
    return f"{n >> 20}M" if n % (1 << 20) == 0 and n >= (1 << 20) else f"{n}_keys"


def _bench_analyze_smoke(args, cfg: SortConfig) -> int:
    """`dsort bench --analyze-smoke`: the introspection plane's own cost.

    The `make profile-smoke` target (tier-1-gated like the other smokes).
    Runs the same ring sort with and without the full introspection stack
    attached — journal, compile ledger drain, memwatch tap — and emits ONE
    JSON line whose ``overhead_frac`` is the measured cost of observing
    (< 5% is the contract, the row's exit code enforces it).  The same
    run also exercises the analyzer end to end: a zipf ring run's journal
    must yield a skew ratio measurably above the uniform run's, and the
    verdict's dominant phase and compile split ride along in the row.
    """
    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_uniform, gen_zipf
    from dsort_tpu.obs.analyze import analyze_records
    from dsort_tpu.obs.prof import MemWatch
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.utils.events import EventLog

    mesh = local_device_mesh(cfg.mesh.num_workers)
    if mesh.shape["w"] < 2:
        raise SystemExit(
            "--analyze-smoke needs a multi-worker mesh (the skew report "
            "rides the ring plan); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    ss = SampleSort(
        mesh, JobConfig(key_dtype=np.int64, local_kernel=cfg.job.local_kernel)
    )
    n = args.n
    uni = gen_uniform(n, dtype=np.int64, seed=0)
    zipf = gen_zipf(n, a=1.3, seed=4)

    def timed(data, journal=None, memwatch=False):
        times, log_ = [], journal
        for _ in range(args.reps):
            m = Metrics(journal=log_)
            if memwatch:
                MemWatch().attach(m)
            t0 = time.perf_counter()
            ss.sort(data, metrics=m, exchange="ring")
            times.append(time.perf_counter() - t0)
        return float(min(times))  # one-sided jitter doctrine

    ss.sort(uni, exchange="ring")   # warm/compile both plans
    ss.sort(zipf, exchange="ring")
    bare_s = timed(uni)
    uni_journal = EventLog()
    # The overhead under test is the ALWAYS-ON plane: journal + compile
    # ledger.  The memwatch tap is an opt-in flag (each snapshot walks the
    # backend's live allocations — worth paying when hunting HBM, not a
    # tax every job should carry), so it rides the verdict-exercise run
    # below, outside the timed A/B.
    obs_s = timed(uni, journal=uni_journal)
    overhead = (obs_s - bare_s) / bare_s
    zipf_journal = EventLog()
    mz = Metrics(journal=zipf_journal)
    MemWatch().attach(mz)
    ss.sort(zipf, metrics=mz, exchange="ring")
    vz = analyze_records([e.to_dict() for e in zipf_journal.events()])
    vu = analyze_records([e.to_dict() for e in uni_journal.events()])
    skew_z = (vz.get("skew") or {}).get("max_mean_ratio", 0.0)
    skew_u = (vu.get("skew") or {}).get("max_mean_ratio", 0.0)
    if getattr(args, "journal", None):
        zipf_journal.flush_jsonl(args.journal)
    # The < 5% contract binds at the 1M row (BENCH_r09.jsonl); below it a
    # single sort is fast enough that scheduler jitter, not the journal,
    # dominates the A/B — the small-n gate checks the plane end to end,
    # the big-n run checks its price.
    overhead_ok = overhead < 0.05 or n < (1 << 20)
    ok = overhead_ok and skew_u > 0 and skew_z > skew_u
    print(json.dumps({
        "metric": (
            "analyze_overhead_1M" if n == 1 << 20
            else f"analyze_overhead_{n}_keys"
        ),
        "value": round(max(overhead, 0.0), 4),
        "unit": "frac",
        "overhead_frac": round(overhead, 4),
        "bare_keys_per_sec": round(n / bare_s, 1),
        "journaled_keys_per_sec": round(n / obs_s, 1),
        "dominant_phase": str(vz.get("dominant_phase")),
        "skew_ratio_zipf": round(skew_z, 3),
        "skew_ratio_uniform": round(skew_u, 3),
        "hbm_watermark_bytes": int((vz.get("hbm") or {}).get("bytes_in_use", 0)),
        "introspection_ok": ok,
    }), flush=True)
    return 0 if ok else 1


def _bench_fleet_mixed(args, cfg: SortConfig) -> int:
    """`dsort bench --fleet-mixed`: the federated serving benchmark.

    The `make fleet-smoke` target and THE acceptance harness for the
    fleet plane (ARCHITECTURE §12): TWO local execution agents — each a
    real `FleetAgent` over its own half of the device mesh, spoken to
    over real TCP — behind a `FleetController`, driven with a mixed
    workload (4 small jobs x 3 tenants at two repeat sizes, twice, plus
    one large full-mesh job) under BOTH routing policies.  The A/B axis
    is variant-cache locality: under ``routing="locality"`` repeat-size
    jobs stick to the agent that already compiled their ladder rung,
    under ``routing="random"`` they scatter and both agents pay the
    compile — the row carries both fleet-wide hit rates and exits
    nonzero unless locality wins AND every output is bit-identical to
    ``np.sort``.  Fairness (p95 queue-wait ratio across tenants, from
    the controller journal's ``job_dequeued`` records) must hold the
    same 3x bound the PR 7 serving layer is tested to.

    ISSUE 14 adds two arms: a heartbeats-only locality baseline (health
    telemetry off) whose elapsed-time ratio against the locality arm is
    ``telemetry_overhead_frac`` (the <5% live-telemetry contract), and a
    ``routing="health"`` arm emitting its own
    ``fleet_mixed_health_routing_2agents`` row (rolling verdict count,
    hit rate, speedup vs locality) — the drilled route-around-a-straggler
    behavior lives in ``tests/test_health.py``.
    """
    import dataclasses
    import tempfile

    import jax

    from dsort_tpu.fleet.agent import FleetAgent
    from dsort_tpu.fleet.controller import FleetController
    from dsort_tpu.serve import SortService
    from dsort_tpu.utils.events import EventLog

    devs = jax.devices()
    n_devs = cfg.mesh.num_workers or len(devs)
    if n_devs < 2:
        raise SystemExit(
            "--fleet-mixed needs >= 2 devices (each agent owns half the "
            "mesh); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    devs = devs[:n_devs]
    half = max(n_devs // 2, 1)
    n_small = max(min(args.n, 1 << 19), 1 << 10)
    n_large = 1 << 20  # >= FLEET_SMALL_JOB_MAX: routes by size
    tenants = ("acme", "blue", "coral")
    rng = np.random.default_rng(0)
    # Two repeat sizes x two rounds: repeat-size jobs are where locality
    # routing must show its cache-hit advantage over random.
    small_jobs = []
    for rnd in range(2):
        for j in range(4):
            for t in tenants:
                n = n_small if j % 2 == 0 else max(n_small // 2, 1 << 9)
                small_jobs.append(
                    (t, rng.integers(0, 1 << 30, n).astype(np.int32))
                )
    large = rng.integers(0, 1 << 30, n_large).astype(np.int32)
    serve_cfg = dataclasses.replace(
        cfg.serve,
        max_queue_depth=max(cfg.serve.max_queue_depth, len(small_jobs) + 8),
        max_tenant_inflight=max(
            cfg.serve.max_tenant_inflight, len(small_jobs) + 2
        ),
    )
    journal = _open_journal(args) or EventLog()

    def run_arm(routing: str, arm_journal, td: str, name: str,
                telemetry_on: bool = True):
        agents = [
            FleetAgent(
                service=SortService(
                    devices=devs[:half], job=cfg.job, serve=serve_cfg
                ),
                agent_id=f"{name}-a",
            ),
            FleetAgent(
                service=SortService(
                    devices=devs[half:], job=cfg.job, serve=serve_cfg
                ),
                agent_id=f"{name}-b",
            ),
        ]
        ctl = FleetController(
            [ag.addr for ag in agents],
            state_dir=os.path.join(td, name),
            max_queue_depth=serve_cfg.max_queue_depth,
            max_tenant_inflight=serve_cfg.max_tenant_inflight,
            routing=routing,
            heartbeat_s=0.5,
            journal=arm_journal,
            health_telemetry=telemetry_on,
        )
        try:
            t0 = time.perf_counter()
            tickets = [
                ctl.submit(d, tenant=t)[1] for t, d in small_jobs
            ]
            tickets.append(ctl.submit(large, tenant="acme")[1])
            ok = True
            for (t, d), ticket in zip(
                small_jobs + [("acme", large)], tickets
            ):
                out = ticket.result(timeout=900)
                ok = ok and bool(np.array_equal(out, np.sort(d)))
            dt = time.perf_counter() - t0
            rerouted = sum(
                1 for e in arm_journal.events() if e.type == "job_rerouted"
            )
            hits = misses = 0
            for ag in agents:
                st = ag.service.variants.stats()
                hits += st["hits"]
                misses += st["misses"]
            hit_rate = hits / (hits + misses) if (hits + misses) else 0.0
            verdicts = sum(
                1 for e in arm_journal.events() if e.type == "health_verdict"
            )
            return dt, ok, hit_rate, rerouted, verdicts
        finally:
            ctl.shutdown(drain=True)
            for ag in agents:
                ag.close()

    rand_journal, health_journal = EventLog(), EventLog()
    reps = max(getattr(args, "reps", 1), 1)
    with tempfile.TemporaryDirectory() as td:
        # The random arm runs FIRST and warms process-wide compile caches,
        # so the arms after it compare on an equal (warm) footing — in
        # particular the telemetry-overhead pair below.
        dt_rand, ok_rand, hit_rand, _, _ = run_arm(
            "random", rand_journal, td, "random"
        )
        # Heartbeats-only baseline vs the live health plane: identical
        # locality workload, telemetry opt-in the ONLY difference — the
        # ratio is the overhead the <5% contract binds on.  Min-of-reps
        # on BOTH sides (the bench doctrine): the per-frame work is tiny
        # and a single elapsed sample is scheduler-noise-dominated.
        dt_hb = dt_loc = None
        ok_hb = ok_loc = True
        for i in range(reps):
            dt, ok, _, _, _ = run_arm(
                "locality", EventLog(), td, f"hb-only{i}",
                telemetry_on=False,
            )
            ok_hb = ok_hb and ok
            dt_hb = dt if dt_hb is None else min(dt_hb, dt)
            dt, ok, hit_loc, rerouted, _ = run_arm(
                "locality", journal, td, f"locality{i}"
            )
            ok_loc = ok_loc and ok
            dt_loc = dt if dt_loc is None else min(dt_loc, dt)
        dt_health, ok_health, hit_health, _, verdicts = run_arm(
            "health", health_journal, td, "health"
        )
    try:
        if getattr(args, "journal", None):
            journal.flush_jsonl(args.journal)
    except OSError as e:
        log.warning("fleet-mixed journal write failed: %s", e)
    p95, fairness = _queue_fairness(journal.events(), tenants)
    ok = (
        ok_rand and ok_loc and ok_hb and ok_health and hit_loc > hit_rand
        and verdicts > 0
    )
    jobs_total = len(small_jobs) + 1
    print(json.dumps({
        "metric": "fleet_mixed_workload_2agents",
        "value": round(jobs_total / dt_loc, 2),
        "unit": "jobs/sec",
        "jobs": jobs_total,
        "tenants": len(tenants),
        "agents": 2,
        "cache_hit_rate": round(hit_loc, 3),
        "cache_hit_rate_random": round(hit_rand, 3),
        "p95_queue_wait_ms": round(p95 * 1e3, 2),
        "fairness_p95_ratio": round(fairness, 2),
        "speedup_vs_random": round(dt_rand / dt_loc, 2),
        "rerouted": rerouted,
        "telemetry_overhead_frac": round(dt_loc / dt_hb - 1.0, 4),
        "bit_identical": ok_rand and ok_loc,
    }), flush=True)
    print(json.dumps({
        "metric": "fleet_mixed_health_routing_2agents",
        "value": round(jobs_total / dt_health, 2),
        "unit": "jobs/sec",
        "jobs": jobs_total,
        "tenants": len(tenants),
        "agents": 2,
        "cache_hit_rate": round(hit_health, 3),
        "health_verdicts": verdicts,
        "speedup_vs_locality": round(dt_loc / dt_health, 2),
        "bit_identical": ok_health,
    }), flush=True)
    return 0 if ok else 1


def cmd_bench(args) -> int:
    from dsort_tpu.data.ingest import gen_uniform

    if args.reps < 1:
        raise SystemExit("--reps must be >= 1")
    if getattr(args, "coded_v2_ab", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ) or getattr(args, "external_wave", False) or getattr(
            args, "fleet_mixed", False
        ) or getattr(args, "coded_ab", False) or getattr(
            args, "autotune_ab", False
        ) or getattr(args, "hier_ab", False):
            raise SystemExit(
                "--coded-v2-ab is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_coded_v2_ab(args, _load_config(args))
    if getattr(args, "hier_ab", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ) or getattr(args, "external_wave", False) or getattr(
            args, "fleet_mixed", False
        ) or getattr(args, "coded_ab", False) or getattr(
            args, "autotune_ab", False
        ):
            raise SystemExit(
                "--hier-ab is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_hier_ab(args, _load_config(args))
    if getattr(args, "autotune_ab", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ) or getattr(args, "external_wave", False) or getattr(
            args, "fleet_mixed", False
        ) or getattr(args, "coded_ab", False):
            raise SystemExit(
                "--autotune-ab is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_autotune_ab(args, _load_config(args))
    if getattr(args, "coded_ab", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ) or getattr(args, "external_wave", False) or getattr(
            args, "fleet_mixed", False
        ):
            raise SystemExit(
                "--coded-ab is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_coded_ab(args, _load_config(args))
    if getattr(args, "fleet_mixed", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ) or getattr(args, "external_wave", False):
            raise SystemExit(
                "--fleet-mixed is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_fleet_mixed(args, _load_config(args))
    if getattr(args, "external_wave", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False) or getattr(
            args, "analyze_smoke", False
        ):
            raise SystemExit(
                "--external-wave is its own benchmark: run it as a "
                "separate invocation"
            )
        return _bench_external_wave(args, _load_config(args))
    if getattr(args, "analyze_smoke", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ) or getattr(args, "serve_mixed", False):
            raise SystemExit(
                "--analyze-smoke is its own benchmark: run it as a "
                "separate invocation"
            )
        return _bench_analyze_smoke(args, _load_config(args))
    if getattr(args, "serve_mixed", False):
        if args.suite or getattr(args, "device_resident", False) or getattr(
            args, "exchange_ab", False
        ):
            raise SystemExit(
                "--serve-mixed is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_serve_mixed(args, _load_config(args))
    if getattr(args, "exchange_ab", False):
        if args.suite or getattr(args, "device_resident", False):
            raise SystemExit(
                "--exchange-ab is its own benchmark: run it as a separate "
                "invocation"
            )
        return _bench_exchange_ab(args, _load_config(args))
    if args.suite and getattr(args, "device_resident", False):
        # The ladder has its own metric contract; silently dropping one of
        # two explicit flags would ship an artifact missing the lines the
        # user asked for.
        raise SystemExit(
            "--suite and --device-resident are separate benchmarks: run "
            "them as two invocations"
        )
    if args.suite:
        return _bench_suite(args)
    cfg = _load_config(args)
    if getattr(args, "device_resident", False):
        if args.mode != "spmd":
            raise SystemExit("--device-resident requires --mode spmd")
        return _bench_device_resident(args, cfg)
    sorter = _make_sorter(cfg, args.mode)
    data = gen_uniform(args.n, dtype=np.dtype(cfg.job.key_dtype), seed=0)
    journal = _open_journal(args)
    sorter(data, Metrics())  # warm/compile
    times = []
    try:
        for _ in range(args.reps):
            m = Metrics(journal=journal)
            _maybe_memwatch(args, m)
            t0 = time.perf_counter()
            sorter(data, m)
            times.append(time.perf_counter() - t0)
    finally:
        # Same discipline as run/serve/batch: a rep that crashes must not
        # lose the journal of the reps that did complete.
        _write_journal(journal, args)
    dt = float(min(times))  # one-sided tunnel jitter; see _bench_suite
    print(
        json.dumps(
            {
                "metric": f"sort_throughput_{np.dtype(cfg.job.key_dtype)}_{args.n}_keys_{args.mode}",
                "value": round(args.n / dt, 1),
                "unit": "keys/sec",
                "vs_baseline": round(args.n / dt / _REF_KEYS_PER_SEC, 2),
            }
        )
    )
    return 0


def cmd_batch(args) -> int:
    """Sort MANY files as one batched SPMD program (the `MeshConfig.dp` axis).

    The reference serves its REPL one job at a time (``server.c:160-167``);
    `BatchSampleSort` runs a whole batch concurrently over a ``(dp, w)``
    mesh — jobs batch over ``dp``, each job's keys shard over ``w``.  Each
    input FILE writes ``<outdir>/<basename>`` sorted.
    """
    import dataclasses

    from dsort_tpu.config import ConfigError
    from dsort_tpu.data.ingest import read_ints_file, write_ints_file
    from dsort_tpu.parallel.mesh import make_mesh
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    cfg = _load_config(args)
    dtype = np.dtype(cfg.job.key_dtype)
    # Outputs land at outdir/<basename>; two inputs sharing a basename would
    # silently overwrite each other — refuse up front (code-review r3).
    names = [os.path.basename(p) for p in args.inputs]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise SystemExit(
            f"duplicate input basenames would overwrite each other in "
            f"--outdir: {dupes}"
        )
    # Mesh sizing/validation is make_mesh's job (it computes w from the
    # visible devices and rejects overcommit), not re-derived here.
    mesh_cfg = dataclasses.replace(cfg.mesh, dp=args.dp or cfg.mesh.dp)
    try:
        mesh = make_mesh(mesh_cfg)
    except ConfigError as e:
        raise SystemExit(str(e))
    dp = int(mesh.shape[mesh_cfg.dp_axis_name])
    w = int(mesh.shape[mesh_cfg.axis_name])
    os.makedirs(args.outdir, exist_ok=True)
    t0 = time.perf_counter()
    jobs = [read_ints_file(p, dtype=dtype) for p in args.inputs]
    journal = _open_journal(args)
    metrics = Metrics(journal=journal)
    # With --checkpoint-dir each file's sorted result persists under its
    # basename: a killed batch re-run restores completed files and re-packs
    # the buckets over the missing ones (VERDICT r3 #7).  Ids must be
    # deduplicated AFTER sanitization — distinct basenames like 'a b.txt'
    # and 'a_b.txt' map to one id, and two jobs sharing a checkpoint id
    # would fingerprint-clear each other every run.
    job_ids = None
    if cfg.job.checkpoint_dir:
        job_ids = [_job_id_for(p, None) for p in args.inputs]
        id_dupes = sorted({j for j in job_ids if job_ids.count(j) > 1})
        if id_dupes:
            raise SystemExit(
                "these inputs sanitize to the same checkpoint id(s) "
                f"{id_dupes}; rename the files or drop --checkpoint-dir"
            )
    try:
        outs = BatchSampleSort(mesh, cfg.job).sort(
            jobs, metrics=metrics, job_ids=job_ids
        )
    finally:
        _write_journal(journal, args)
    for src, out in zip(args.inputs, outs):
        write_ints_file(os.path.join(args.outdir, os.path.basename(src)), out)
    dt = time.perf_counter() - t0
    log.info(
        "batch-sorted %d jobs (%d keys total) in %.1f ms on a (dp=%d, w=%d) "
        "mesh -> %s | phases: %s",
        len(jobs), sum(len(j) for j in jobs), dt * 1e3, dp, w, args.outdir,
        metrics.summary()["phases_ms"],
    )
    return 0


def cmd_gen(args) -> int:
    from dsort_tpu.data.ingest import (
        gen_terasort_file,
        gen_uniform,
        gen_uniform_bin_file,
        gen_zipf,
        write_ints_file,
    )

    if args.dist == "terasort":
        if args.format == "bin":
            # TeraSort output is ALWAYS binary 100-byte records; a --format
            # bin here would silently be ignored while the user expects raw
            # keys — refuse loudly instead (code-review r3).
            raise SystemExit(
                "--format bin is for raw key files; --dist terasort always "
                "writes binary 100-byte records (drop --format)"
            )
        gen_terasort_file(args.output, args.n, seed=args.seed)
        log.info("wrote %d terasort records to %s", args.n, args.output)
        return 0
    if args.format == "bin":
        # Raw binary keys (ExternalSort's input format), streamed in bounded
        # memory — the only practical format at 10^9-key scale.
        if args.dist != "uniform":
            raise SystemExit("--format bin supports --dist uniform only")
        gen_uniform_bin_file(
            args.output, args.n, dtype=np.dtype(args.dtype), seed=args.seed
        )
        log.info("wrote %d %s binary keys to %s", args.n, args.dtype, args.output)
        return 0
    if args.dist == "uniform":
        data = gen_uniform(args.n, dtype=np.dtype(args.dtype), seed=args.seed)
    else:
        data = gen_zipf(
            args.n, a=args.zipf_a, dtype=np.dtype(args.dtype), seed=args.seed
        )
    write_ints_file(args.output, data)
    log.info("wrote %d %s keys (%s) to %s", args.n, args.dtype, args.dist, args.output)
    return 0


def cmd_terasort(args) -> int:
    """Sort a binary TeraSort record file (BASELINE config #4)."""
    import jax

    from dsort_tpu.data.ingest import (
        read_terasort_file,
        terasort_secondary,
        write_terasort_file,
    )
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.config import JobConfig

    # The exchange knob, conf-key parity with `dsort run`/`dsort external`:
    # an explicit --exchange flag wins, then a conf EXCHANGE key, then the
    # JobConfig default (same precedence ladder as _load_config).
    conf_job = SortConfig.from_conf_file(args.conf).job if args.conf else None
    exchange = getattr(args, "exchange", None) or (
        conf_job.exchange if conf_job else None
    )
    hier_hosts = getattr(args, "hier_hosts", None) or (
        conf_job.hier_hosts if conf_job else 0
    )
    if args.external:
        from dsort_tpu.models.external_sort import ExternalTeraSort

        mesh_n = getattr(args, "mesh", None)
        if mesh_n is None and args.conf:
            mesh_n = SortConfig.from_conf_file(args.conf).external.mesh
        journal = _open_journal(args)
        metrics = Metrics(journal=journal)
        _maybe_memwatch(args, metrics)
        t0 = time.perf_counter()
        try:
            if mesh_n:
                # Wave pipeline: mesh-parallel run generation, host spill/
                # merge overlapping the next wave's device work.
                from dsort_tpu.models.wave_sort import ExternalWaveTeraSort
                from dsort_tpu.parallel.mesh import local_device_mesh

                s = ExternalWaveTeraSort(
                    mesh=local_device_mesh(mesh_n),
                    wave_recs=args.run_recs,
                    spill_dir=args.spill_dir,
                    job_id=args.job_id,
                    resume=not args.no_resume,
                    job=conf_job,
                    exchange=getattr(args, "exchange", None),
                )
            else:
                if args.workers is not None:
                    log.warning(
                        "--workers needs the wave pipeline: pass --mesh N "
                        "to make run generation mesh-parallel (without it, "
                        "external run generation is single-device and only "
                        "the merge parallelizes over host cores)"
                    )
                if exchange:
                    log.warning(
                        "--exchange has no effect without --mesh: the "
                        "single-device external record sort has no "
                        "exchange; add --mesh N to run record waves"
                    )
                s = ExternalTeraSort(
                    run_recs=args.run_recs,
                    spill_dir=args.spill_dir,
                    job_id=args.job_id,
                    resume=not args.no_resume,
                )
            s.sort_file(
                args.input, args.output or "terasort_out.bin", metrics=metrics
            )
        finally:
            _write_journal(journal, args)
        dt = time.perf_counter() - t0
        n = os.path.getsize(args.input) // ExternalTeraSort.RECORD_BYTES
        log.info(
            "terasort (external%s): %d records in %.1f ms (%.2f Mrec/s) | %s"
            " | phases: %s",
            f", {mesh_n}-device waves" if mesh_n else "",
            n, dt * 1e3, n / dt / 1e6, dict(metrics.counters),
            metrics.summary()["phases_ms"],
        )
        return 0

    keys, payload = read_terasort_file(args.input)
    mesh = local_device_mesh(args.workers)
    job = JobConfig(
        key_dtype=np.uint64, payload_bytes=payload.shape[1],
        exchange=exchange or JobConfig.exchange,
        hier_hosts=hier_hosts or JobConfig.hier_hosts,
    )
    metrics = Metrics()
    t0 = time.perf_counter()
    sk, sv = SampleSort(mesh, job).sort_kv(
        keys, payload, metrics=metrics, secondary=terasort_secondary(payload),
        exchange=getattr(args, "exchange", None),
    )
    dt = time.perf_counter() - t0
    write_terasort_file(args.output or "terasort_out.bin", sk, sv)
    log.info(
        "terasort: %d records in %.1f ms (%.2f Mrec/s) | phases: %s",
        len(keys), dt * 1e3, len(keys) / dt / 1e6, metrics.summary()["phases_ms"],
    )
    return 0


def cmd_external(args) -> int:
    """Out-of-core sort of a raw binary key file.

    Default: the single-device run/merge pipeline
    (`models.external_sort.ExternalSort`).  With ``--mesh N`` (or conf
    ``EXTERNAL_MESH``) the dataset runs through the WAVE pipeline
    (`models.wave_sort.ExternalWaveSort`): device-budget-sized waves are
    range-partitioned and ring-exchanged over the mesh while the previous
    wave's runs spill on the host — datasets far larger than the mesh's
    memory sort at device speed, resumable at (wave, run) granularity.
    Flags override conf keys (``EXTERNAL_RUN_ELEMS`` /
    ``EXTERNAL_WAVE_ELEMS`` / ``EXTERNAL_MESH``), same precedence as the
    serving layer's ``SERVE_*``.
    """
    ext = (
        SortConfig.from_conf_file(args.conf).external if args.conf
        else SortConfig().external
    )
    run_elems = args.run_elems if args.run_elems is not None else ext.run_elems
    wave_elems = (
        args.wave_elems if args.wave_elems is not None else ext.wave_elems
    )
    mesh_n = args.mesh if args.mesh is not None else ext.mesh
    journal = _open_journal(args)
    metrics = Metrics(journal=journal)
    _maybe_memwatch(args, metrics)
    t0 = time.perf_counter()
    try:
        if mesh_n:
            from dsort_tpu.models.wave_sort import ExternalWaveSort
            from dsort_tpu.parallel.mesh import local_device_mesh

            from dsort_tpu.config import JobConfig

            job_kw = {}
            if args.kernel:
                job_kw["local_kernel"] = args.kernel
            if getattr(args, "hier_hosts", None):
                job_kw["hier_hosts"] = args.hier_hosts
            s = ExternalWaveSort(
                mesh=local_device_mesh(mesh_n),
                wave_elems=wave_elems,
                spill_dir=args.spill_dir,
                job_id=args.job_id,
                job=JobConfig(**job_kw) if job_kw else None,
                resume=not args.no_resume,
                overlap=not getattr(args, "no_overlap", False),
                exchange=getattr(args, "exchange", None),
                redundancy=getattr(args, "redundancy", None),
                redundancy_mode=getattr(args, "redundancy_mode", None),
            )
        else:
            from dsort_tpu.models.external_sort import ExternalSort

            if getattr(args, "exchange", None):
                log.warning(
                    "--exchange has no effect without --mesh: the "
                    "single-device external sort has no exchange; add "
                    "--mesh N to run the wave pipeline"
                )
            if getattr(args, "redundancy", None) and args.redundancy > 1:
                # Louder than the --exchange case: a silently-dropped
                # availability posture would leave the operator believing
                # device-loss tolerance is active when it is not.
                log.warning(
                    "--redundancy has no effect without --mesh: the "
                    "single-device external sort has no replica plane; "
                    "add --mesh N to run coded waves"
                )
            s = ExternalSort(
                run_elems=run_elems,
                spill_dir=args.spill_dir,
                job_id=args.job_id,
                local_kernel=args.kernel or "auto",
                resume=not args.no_resume,
            )
        s.sort_binary_file(
            args.input, args.output, dtype=np.dtype(args.dtype or "int32"),
            metrics=metrics,
        )
    finally:
        # Journal parity with `dsort run`: the fault/resume timeline (wave
        # events included) must land on disk even when the job fails.
        _write_journal(journal, args)
    dt = time.perf_counter() - t0
    log.info(
        "external-sorted %s -> %s in %.1f ms%s | %s | phases: %s",
        args.input, args.output, dt * 1e3,
        f" ({mesh_n}-device waves)" if mesh_n else "",
        dict(metrics.counters), metrics.summary()["phases_ms"],
    )
    return 0


def cmd_validate(args) -> int:
    """Validate a sort output (valsort role): order + permutation-of-input."""
    from dsort_tpu.models.validate import (
        checksum_bin_file,
        checksum_ints_file,
        checksum_terasort_file,
        validate_bin_file,
        validate_ints_file,
        validate_terasort_file,
    )

    if args.terasort:
        rep = validate_terasort_file(args.input)
    elif args.binary:
        rep = validate_bin_file(args.input, dtype=np.dtype(args.dtype))
    else:
        rep = validate_ints_file(args.input, dtype=np.dtype(args.dtype))
    result = {
        "records": rep.records,
        "sorted": rep.sorted_ok,
        "checksum": f"{rep.checksum:016x}",
    }
    if rep.first_violation is not None:
        result["first_violation"] = rep.first_violation
    ok = rep.sorted_ok
    if args.against:
        if args.terasort:
            n_in, sum_in = checksum_terasort_file(args.against)
        elif args.binary:
            n_in, sum_in = checksum_bin_file(args.against, dtype=np.dtype(args.dtype))
        else:
            n_in, sum_in = checksum_ints_file(args.against, dtype=np.dtype(args.dtype))
        result["permutation_of_input"] = (
            n_in == rep.records and sum_in == rep.checksum
        )
        ok = ok and result["permutation_of_input"]
    print(json.dumps(result))
    return 0 if ok else 1


def cmd_report(args) -> int:
    """Render event journal(s): human timeline + phase/counter tables.

    With several journals (``dsort report --merge a.jsonl b.jsonl`` — the
    ``--merge`` flag is implied by passing more than one) the per-process
    traces merge into ONE aligned fleet timeline (`obs.merge`: each
    journal's monotonic base is rebased via its wall<->mono offset, every
    record tagged with its source).  Each positional path expands to its
    rotated set (``--journal-rotate-mb`` pieces stitch back into one
    journal, never mistaken for a second process).  Torn or malformed
    lines are skipped and counted, never fatal.  ``--chrome-trace``
    additionally exports a Perfetto ``trace_event`` file (one pid per
    source journal, one tid per job) that loads next to a
    ``jax.profiler`` capture.

    ``--analyze`` replays the records through `obs.analyze` instead of
    printing the timeline: phase waterfall with the cross-process
    critical path, straggler attribution, queue-wait/compile/execute
    split, wire bytes (priced against ``--link-mbps`` when given), skew
    and HBM watermarks — the why-slow verdict.  ``--analyze-json PATH``
    additionally writes the machine-readable verdict.
    """
    import json as _json

    from dsort_tpu.obs.merge import (
        expand_path_args,
        group_rotated,
        merge_records,
        read_journal_set,
    )
    from dsort_tpu.utils.events import format_report, to_chrome_trace

    try:
        # Fleet runs produce N journals per run: a positional arg may be a
        # directory or glob of per-agent journals, expanded here before the
        # rotation-set grouping (a rotated piece inside a directory still
        # stitches into its base journal, never a phantom process).
        paths = expand_path_args(args.journal)
    except ValueError as e:
        raise SystemExit(f"dsort report: {e}")
    sources = group_rotated(paths)
    journals, skipped = [], 0
    for s in sources:
        recs, sk = read_journal_set(s)
        journals.append(recs)
        skipped += sk
    if len(journals) > 1 or args.merge:
        records = merge_records(journals)
    else:
        records = journals[0]
    if skipped:
        log.warning("skipped %d malformed journal line(s)", skipped)
    rc = 0
    if args.conform:
        # Trace-contract conformance (ARCHITECTURE §16): the journal
        # replayed against the declared TRACE_CONTRACTS grammars.  A
        # violation exits 1 — this is a gate, not a report.
        from dsort_tpu.analysis.spec.contracts import (
            conformance_report,
            format_conformance,
        )

        conf = conformance_report(records)
        print(format_conformance(conf), end="")
        if not conf["ok"]:
            rc = 1
    if args.analyze or args.analyze_json:
        from dsort_tpu.obs.analyze import analyze_records, format_analysis

        link = (args.link_mbps * 1e6 / 8) if args.link_mbps else None
        verdict = analyze_records(records, link_bytes_per_s=link)
        print(format_analysis(verdict), end="")
        if args.analyze_json:
            with open(args.analyze_json, "w", encoding="utf-8") as f:
                _json.dump(verdict, f, indent=1)
            log.info("analysis verdict written to %s", args.analyze_json)
    elif not args.conform:
        print(format_report(records), end="")
    if args.chrome_trace:
        with open(args.chrome_trace, "w", encoding="utf-8") as f:
            _json.dump(to_chrome_trace(records), f)
        log.info("chrome trace written to %s (load in Perfetto / "
                 "chrome://tracing)", args.chrome_trace)
    return rc


def cmd_top(args) -> int:
    """One-shot (or ``--interval`` refreshing) console view of metrics
    endpoint scrape(s) — the operator's `top` for a running ``dsort serve
    --metrics-port`` session, or, with SEVERAL URLs (the fleet
    controller's endpoint plus one per agent), the per-mesh fleet view
    with combined admissions/cache tables (ARCHITECTURE §12)."""
    from dsort_tpu.obs.top import fetch_metrics, render_fleet, render_top

    urls = args.url or ["http://127.0.0.1:9100/metrics"]
    shown = 0
    while True:
        scrapes, unreachable = [], []
        for url in urls:
            try:
                scrapes.append((url, fetch_metrics(url)))
            except (OSError, ValueError) as e:
                log.error("scrape of %s failed: %s", url, e)
                unreachable.append(url)
        if not scrapes:
            return 1
        if shown:
            print()  # separate refreshes; no terminal tricks needed
        if len(urls) == 1:
            print(f"dsort top — {urls[0]}")
            print(render_top(scrapes[0][1]), end="")
        else:
            # A fleet view must render the REACHABLE meshes while one
            # agent restarts — that is exactly when the operator looks.
            print(f"dsort top — {len(scrapes)}/{len(urls)} sources")
            print(render_fleet(scrapes), end="")
            for url in unreachable:
                print(f"  (unreachable: {url})")
        shown += 1
        if args.interval is None or (args.count and shown >= args.count):
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            print()
            return 0


def _project_root(start: str) -> str:
    """Nearest ancestor holding pyproject.toml (fallback: ``start``)."""
    d = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def _git_changed_files(root: str) -> list[str]:
    """Lintable files changed vs HEAD (worktree + index) plus untracked
    ones, absolute paths.  Loud on any git failure — a broken `--changed`
    must never pass vacuously as "0 files changed"."""
    import subprocess

    def run(*argv):
        r = subprocess.run(
            ["git", "-C", root, *argv], capture_output=True, text=True,
        )
        if r.returncode != 0:
            raise SystemExit(
                f"dsort lint --changed: git {' '.join(argv)} failed: "
                f"{r.stderr.strip() or r.returncode}"
            )
        return r.stdout.splitlines()

    # --relative anchors diff paths at `root` (not the git toplevel).
    names = set(run("diff", "--name-only", "--relative", "HEAD"))
    names.update(run("ls-files", "--others", "--exclude-standard"))
    from dsort_tpu.analysis.engine import _LINTABLE

    out = []
    for name in sorted(names):
        path = os.path.join(root, name)
        if name.endswith(_LINTABLE) and os.path.exists(path):
            out.append(path)
    return out


def cmd_lint(args) -> int:
    """Run the project-native static analysis suite (`dsort_tpu.analysis`).

    Checks the invariants the fault-tolerance story rests on — registry
    coverage (Python AND the C++ coordinator's event vocabulary),
    lock discipline, tracing hygiene, recovery-path exception hygiene,
    compat-shim routing, import-layer purity, durability discipline,
    protocol coverage, kernel/thread lifecycle — without running a
    cluster or touching a backend.  Exit 0 = clean (modulo baseline),
    1 = findings.
    """
    from dsort_tpu.analysis import (
        LintStats,
        format_json,
        format_sarif,
        format_text,
        lint_paths,
        load_config,
        write_baseline,
    )

    root = args.root or _project_root(os.getcwd())
    cfg = load_config(root)
    if args.baseline:
        cfg.baseline = args.baseline
    # Content-hash result cache: `make lint` stays interactive on the
    # grown tree (invalidated by any checker/config/registry change).
    cache_path = (
        None if args.no_cache else os.path.join(root, ".lint-cache.json")
    )
    if args.changed:
        if args.paths:
            raise SystemExit(
                "dsort lint: --changed and explicit paths are exclusive"
            )
        if args.write_baseline:
            # The baseline is a whole-tree artifact: regenerating it from
            # a changed-files subset would silently drop every tolerated
            # entry for unchanged files.
            raise SystemExit(
                "dsort lint: --changed and --write-baseline are exclusive "
                "(the baseline must be regenerated from the full tree)"
            )
        # Scope to the DEFAULT lint target (the package tree) when it
        # exists: a touched test fixture is bad by design and must not
        # fail the pre-commit pass.  A root without the package (another
        # project borrowing the linter) keeps the root-wide scope.
        paths = _git_changed_files(root)
        target = os.path.join(root, "dsort_tpu")
        if os.path.isdir(target):
            target += os.sep
            paths = [p for p in paths if p.startswith(target)]
        if not paths:
            sys.stdout.write("dsort lint: no changed lintable files\n")
            return 0
    else:
        # User-given paths resolve against CWD (normal CLI semantics); only
        # the default target is root-relative.  A missing path is a loud
        # error — a typo'd CI invocation must never pass vacuously as
        # "0 findings".
        paths = [os.path.abspath(p) for p in args.paths] or [
            os.path.join(root, "dsort_tpu")
        ]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise SystemExit(f"dsort lint: no such path(s): {missing}")
    if args.write_baseline:
        # Capture EVERYTHING the tree currently shows: linting through the
        # existing baseline would drop already-tolerated findings and the
        # rewrite would erase them — regenerating must be idempotent.
        path = cfg.abspath(cfg.baseline) or os.path.join(
            root, ".lint-baseline.json"
        )
        cfg.baseline = None
        diags = lint_paths(paths, cfg, cache_path=cache_path)
        write_baseline(path, diags)
        log.info("baseline written to %s (%d entries)", path, len(diags))
        return 0
    stats = LintStats() if args.stats else None
    diags = lint_paths(paths, cfg, cache_path=cache_path, stats=stats)
    formatter = {"json": format_json, "sarif": format_sarif}.get(
        args.format, format_text
    )
    sys.stdout.write(formatter(diags))
    if stats is not None:
        # Stats go to stderr so `--format sarif > out.sarif` stays a valid
        # SARIF document with the table still visible.
        sys.stderr.write(stats.format())
    return 1 if any(d.severity == "error" for d in diags) else 0


def cmd_spec(args) -> int:
    """Protocol spec plane (`dsort_tpu.analysis.spec`, ARCHITECTURE §16).

    ``dsort spec check`` explores bounded interleavings of the fleet
    protocol — frame delivery on FIFO links, retransmission, dispatch
    timeouts, link death/re-attach, controller crash+restore — with the
    REAL `ControlPolicy` embedded via its ``state_dict`` round-trip, and
    checks every reached state against the safety invariant catalog
    (`SPEC_INVARIANTS`).  A violation is minimized to a deterministic
    schedule and (with ``--dump-fixture``) written as a replayable JSON
    fixture.  Exit 0 = no violation in the explored space; 1 = violation.

    ``dsort spec replay --fixture F`` re-executes a dumped schedule and
    exits 0 iff it still reproduces its recorded invariant violation —
    the regression contract for ``tests/data/spec/`` fixtures.

    Backend-free by design: like ``lint``, this command never initializes
    JAX (the model is pure control-plane state).
    """
    from dsort_tpu.analysis.spec.model import (
        ModelConfig,
        check_model,
        dump_fixture,
        format_result,
        load_fixture,
        replay_schedule,
    )

    seams = tuple(args.seam or ())
    if args.action == "replay":
        if not args.fixture:
            raise SystemExit("dsort spec replay: --fixture is required")
        schedule, cfg, fseams = load_fixture(args.fixture)
        violation = replay_schedule(schedule, cfg, fseams)
        if violation is None:
            print(f"{args.fixture}: schedule no longer violates anything")
            return 1
        print(
            f"{args.fixture}: reproduces {violation.invariant} after "
            f"{len(violation.schedule)} action(s): {violation.detail}"
        )
        return 0
    cfg = ModelConfig(
        n_agents=args.agents, n_jobs=args.jobs,
        max_duplications=args.duplications, max_deaths=args.deaths,
        max_crashes=args.crashes,
    )
    result = check_model(
        cfg, seams=seams, max_states=args.max_states,
        max_depth=args.max_depth,
    )
    print(format_result(result, seams), end="")
    if result.violation is not None and args.dump_fixture:
        dump_fixture(args.dump_fixture, result.violation, cfg, seams)
        log.info("violation fixture written to %s", args.dump_fixture)
    return 0 if result.ok else 1


def cmd_coordinator(args) -> int:
    """Run the native coordinator and serve REPL jobs over the cluster."""
    from dsort_tpu.runtime import NativeCoordinator
    from dsort_tpu.data.ingest import read_ints_file, write_ints_file

    cfg = _load_config(args)
    dtype = np.dtype(cfg.job.key_dtype)
    nworkers = args.workers or 4
    with NativeCoordinator(
        port=args.port if args.port is not None else cfg.server_port,
        heartbeat_timeout_s=cfg.job.heartbeat_timeout_s,
    ) as coord:
        log.info("coordinator listening on port %d", coord.port)
        coord.wait_workers(nworkers, timeout_s=args.join_timeout)
        log.info("%d workers joined", nworkers)
        journal = _open_journal(args)
        while True:
            try:
                line = input("Enter the filename to sort (or 'exit' to quit): ")
            except EOFError:
                return 0
            except KeyboardInterrupt:
                # server.c:51-59 parity: clean socket close on Ctrl-C — the
                # coordinator's context manager shuts the cluster down.
                print()
                return 0
            name = line.strip()
            if name == "exit" or not name:
                if name == "exit":
                    return 0
                continue
            try:
                data = read_ints_file(name, dtype=dtype)
                metrics = Metrics(journal=journal)
                out = coord.run_job(data, num_shards=nworkers, metrics=metrics)
                write_ints_file(args.output or cfg.output_path, out)
                log.info(
                    "sorted %d keys | live workers %d | reassignments %d",
                    len(data), coord.num_live, coord.reassignments,
                )
            except Exception as e:
                log.error("job failed: %s", e)
            finally:
                # Cumulative across REPL jobs, rewritten after each (same
                # discipline as `dsort serve`): the native cluster's fault
                # timeline lands on disk even when a job fails.
                _write_journal(journal, args)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dsort", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p, mode_default="spmd"):
        p.add_argument("--conf", help="KEY=value conf file (reference format accepted)")
        p.add_argument("--mode", default=mode_default,
                       choices=["spmd", "taskpool", "local"])
        p.add_argument("--workers", type=int)
        p.add_argument("--dtype")
        p.add_argument("--kernel", choices=["auto", "lax", "block", "bitonic", "pallas", "radix"])
        p.add_argument("--merge-kernel",
                       choices=["auto", "sort", "bitonic", "block_merge"],
                       help="post-shuffle combine (default auto: block_merge "
                            "wherever the block kernel applies)")
        p.add_argument("--exchange",
                       choices=["alltoall", "ring", "fused", "hier"],
                       help="bucket exchange schedule (default alltoall; "
                            "ring = chunked ppermute with adaptive per-step "
                            "headroom and merge-as-you-receive; fused = the "
                            "same measured ring schedule as ONE Pallas "
                            "kernel — in-kernel async remote DMAs, P-1 "
                            "dispatches collapsed to one launch; hier = the "
                            "two-level pod schedule: intra-host aggregation "
                            "then ONE merged DCN transfer per host pair, "
                            "ARCHITECTURE §17)")
        p.add_argument("--hier-hosts", type=int,
                       help="host count the hier schedule groups the worker "
                            "mesh into (default 0 = auto: the process count "
                            "when genuinely multi-host, else 2 simulated; "
                            "conf key HIER_HOSTS)")
        p.add_argument("--redundancy", type=int,
                       help="coded redundancy r (default 1 = off): the ring "
                            "exchange additionally ships every bucket to "
                            "its destination's r-1 ring successors, so up "
                            "to r-1 device losses recover by a LOCAL merge "
                            "of replica slots — zero keys re-sorted, zero "
                            "re-dispatch (ARCHITECTURE \u00a714; forces the "
                            "lax ring schedule; conf key REDUNDANCY)")
        p.add_argument("--redundancy-mode",
                       choices=["replicate", "parity"],
                       help="how r > 1 ships its premium (ARCHITECTURE "
                            "\u00a718): 'replicate' = full bucket copies, "
                            "(r-1)x extra wire bytes; 'parity' = XOR (r=2) "
                            "or RAID-6 P+Q GF(256) (r>=3) parity slots \u2014 "
                            "same local-merge recovery at ~1/P x the "
                            "premium (conf key REDUNDANCY_MODE)")
        p.add_argument("--checkpoint-dir",
                       help="persist per-shard/range progress here; a re-run "
                            "of the same input resumes instead of re-sorting")
        p.add_argument("--job-id",
                       help="checkpoint namespace (default: input basename)")
        p.add_argument("--journal",
                       help="write the job's structured event journal "
                            "(JSONL) here; render with `dsort report`")
        p.add_argument("--journal-rotate-mb", type=float,
                       help="rotate the journal to PATH.N at this size so "
                            "a long session never grows one unbounded "
                            "file; `dsort report` stitches the set back")
        p.add_argument("--tenant",
                       help="tenant label on this job's events and SLO "
                            "histograms (default 'default')")
        p.add_argument("--flight-dir",
                       help="fault flight recorder directory: any recovery "
                            "path dumps a postmortem bundle here "
                            "(ring + config + mesh state + counters)")
        p.add_argument("--no-autotune", action="store_true",
                       help="disable the closed-loop planner (obs.plan): no "
                            "measured-signal knob filling, no plan_decision "
                            "events — every knob rides its flag/conf/default "
                            "value exactly (conf AUTOTUNE=0; the planner is "
                            "otherwise ON for CLI runs, and explicit flags "
                            "always win over it either way)")
        p.add_argument("-o", "--output")

    p = sub.add_parser("run", help="sort one file")
    p.add_argument("input")
    p.add_argument("--profile-dir",
                   help="capture a jax.profiler trace of the job here")
    p.add_argument("--device-resident", action="store_true",
                   help="keep the sorted array on the mesh and validate it "
                        "on device (order + multiset checksum as jitted "
                        "reductions); the output file write is the only D2H")
    p.add_argument("--memwatch", action="store_true",
                   help="snapshot device memory at every phase boundary "
                        "into hbm_watermark journal events (obs.prof)")
    common(p)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("serve", help="interactive job loop (reference REPL, "
                                     "served by the async multi-tenant core)")
    common(p)
    p.add_argument("--metrics-port", type=int,
                   help="expose the live telemetry endpoint on this port "
                        "(0 = ephemeral; Prometheus text at /metrics, "
                        "JSON at /json; view with `dsort top`)")
    p.add_argument("--max-in-flight", type=int, default=1,
                   help="REPL jobs in flight at once (default 1 = await "
                        "each job, the reference's blocking semantics; >1 "
                        "= async submit with concurrent mesh-slice packing)")
    p.add_argument("--prewarm", nargs="?", const="auto",
                   choices=("auto", "all"),
                   help="compile fused rungs at startup: 'auto' (the "
                        "default value) compiles the planner's predicted "
                        "rung x dtype set from recent admissions — full "
                        "ladder on a cold start; 'all' keeps the old "
                        "exhaustive ladder (conf SERVE_PREWARM=1|all)")
    p.add_argument("--slice-devices", type=int,
                   help="devices per small-job mesh sub-slice (default 1; "
                        "concurrent small jobs pack onto disjoint slices)")
    p.add_argument("--queue-limit", type=int,
                   help="admission control: max jobs queued service-wide")
    p.add_argument("--tenant-limit", type=int,
                   help="admission control: max queued+running jobs per "
                        "tenant")
    p.add_argument("--weights",
                   help="fair-scheduler tenant weights, e.g. acme=2,blue=1 "
                        "(unlisted tenants weigh 1)")
    p.add_argument("--slo-shed-ms", type=float,
                   help="admission shedding target: reject (verdict "
                        "'slo_shed') while a tenant's live p95 queue wait "
                        "exceeds this many ms with work still queued; "
                        "recovers automatically once the queue drains")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "fleet-agent",
        help="fleet execution agent: serve this process's mesh to a "
             "`dsort fleet` controller (ARCHITECTURE §12)",
    )
    common(p)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for the fleet protocol endpoint")
    p.add_argument("--port", type=int, default=0,
                   help="fleet protocol port (0 = ephemeral, printed at "
                        "startup)")
    p.add_argument("--agent-id",
                   help="stable agent identity (default: generated); a "
                        "restarted agent keeps its routing identity by "
                        "reusing the id")
    p.add_argument("--metrics-port", type=int,
                   help="expose this mesh's live telemetry endpoint "
                        "(render the whole fleet with `dsort top URL...`)")
    p.add_argument("--prewarm", nargs="?", const="auto",
                   choices=("auto", "all"),
                   help="compile fused rungs at startup, advertised to the "
                        "controller for locality routing: 'auto' = the "
                        "planner's predicted set, 'all' = the exhaustive "
                        "ladder")
    p.add_argument("--slice-devices", type=int,
                   help="devices per small-job mesh sub-slice")
    p.add_argument("--queue-limit", type=int,
                   help="this agent's local queue bound")
    p.add_argument("--tenant-limit", type=int,
                   help="this agent's local per-tenant bound")
    p.add_argument("--weights", help=argparse.SUPPRESS)
    p.add_argument("--slo-shed-ms", type=float, help=argparse.SUPPRESS)
    p.add_argument("--max-in-flight", type=int, help=argparse.SUPPRESS)
    p.set_defaults(fn=cmd_fleet_agent)

    p = sub.add_parser(
        "fleet",
        help="fleet controller REPL: route jobs over many mesh-owning "
             "agents; restart-safe (ARCHITECTURE §12)",
    )
    common(p)
    p.add_argument("--agents",
                   help="agent endpoints host:port,host:port (conf "
                        "FLEET_AGENTS)")
    p.add_argument("--state-dir",
                   help="persist the control-plane state here so a "
                        "controller restart loses no job (conf "
                        "FLEET_STATE_DIR)")
    p.add_argument("--routing", choices=["locality", "random", "health"],
                   help="variant-cache-locality routing (default), the "
                        "random A/B baseline, or health — locality for "
                        "small jobs plus live straggler-penalized big-job "
                        "placement from the streamed telemetry verdicts "
                        "(conf FLEET_ROUTING)")
    p.add_argument("--no-health-telemetry", action="store_true",
                   help="heartbeats only: do not opt agents into the "
                        "health plane's bounded delta stream (conf "
                        "FLEET_TELEMETRY=0)")
    p.add_argument("--dispatch-timeout", type=float,
                   help="per-agent send deadline in seconds: a stuck-but-"
                        "connected agent fails over after this long "
                        "(conf FLEET_DISPATCH_TIMEOUT_S; default: the "
                        "request timeout)")
    p.add_argument("--metrics-port", type=int,
                   help="expose the controller's telemetry endpoint")
    p.add_argument("--max-in-flight", type=int, default=1,
                   help="REPL jobs in flight at once (like `dsort serve`)")
    p.add_argument("--queue-limit", type=int,
                   help="admission control: max jobs queued fleet-wide")
    p.add_argument("--tenant-limit", type=int,
                   help="admission control: max queued+running jobs per "
                        "tenant")
    p.add_argument("--weights",
                   help="fair-scheduler tenant weights, e.g. acme=2,blue=1")
    p.add_argument("--slo-shed-ms", type=float,
                   help="admission shedding target (ms, per-tenant live "
                        "p95 queue wait)")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser("bench", help="throughput benchmark (one JSON line)")
    common(p)
    p.add_argument("--n", type=int, default=1 << 22)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--suite", action="store_true",
                   help="run the BASELINE config ladder (one JSON line each)")
    p.add_argument("--device-resident", action="store_true",
                   help="time the no-relay path: device-resident sort + "
                        "on-device validation, one JSON line each")
    p.add_argument("--exchange-ab", action="store_true",
                   help="three-way alltoall/ring/fused exchange A/B on the "
                        "local mesh "
                        "(uniform + zipf; asserts bit-identical outputs, "
                        "reports bytes_on_wire per schedule)")
    p.add_argument("--serve-mixed", action="store_true",
                   help="multi-tenant serving benchmark: a mixed small/large "
                        "three-tenant workload through the real admission "
                        "queue with mesh-slice packing; one JSON line with "
                        "jobs/s, p95 queue wait, fairness ratio, variant-"
                        "cache hit rate and packed-vs-serial speedup")
    p.add_argument("--analyze-smoke", action="store_true",
                   help="introspection-plane cost proof: the same ring "
                        "sort with and without journal+ledger+memwatch "
                        "attached (overhead_frac < 5%% is the contract), "
                        "plus the zipf-vs-uniform skew report margin")
    p.add_argument("--memwatch", action="store_true",
                   help="snapshot device memory at phase boundaries into "
                        "hbm_watermark journal events")
    p.add_argument("--fleet-mixed", action="store_true",
                   help="federated serving benchmark: 2 local mesh-owning "
                        "agents behind a fleet controller over real TCP, "
                        "mixed tenants/sizes, locality-vs-random routing "
                        "A/B; one JSON line with both fleet-wide variant-"
                        "cache hit rates, fairness ratio and bit-identical "
                        "outputs")
    p.add_argument("--coded-ab", action="store_true",
                   help="coded-redundancy failure A/B: the same zipf "
                        "workload at redundancy=1 vs 2, healthy vs one "
                        "injected device loss (bit-identical gate); JSON "
                        "rows with throughput_under_failure_ratio and the "
                        "healthy-path replica overhead")
    p.add_argument("--coded-v2-ab", action="store_true",
                   help="coded-exchange v2 acceptance A/B (ARCHITECTURE "
                        "§18): replicate vs parity at redundancy=2 — "
                        "healthy wire premium (parity < 0.75x replicate's "
                        "coded_replica_bytes), one injected loss per mode "
                        "(both recover locally, zero re-sorted keys), and "
                        "the straggler drill (p99 with serving ON beats "
                        "the measured wait-on-owner baseline, exactly one "
                        "serve per rep); bit-identical gate throughout")
    p.add_argument("--autotune-ab", action="store_true",
                   help="closed-loop planner A/B: zipf + uniform workloads "
                        "with exchange hand-set to alltoall, hand-set to "
                        "ring, and planner-chosen (autotune on, knob "
                        "unset); gates bit-identical outputs, the measured-"
                        "skew pick (ring on zipf, alltoall on uniform) and "
                        "autotune >= 0.95x the best hand-set arm at 1M+")
    p.add_argument("--hier-ab", action="store_true",
                   help="two-level pod exchange A/B: flat ring vs hier at "
                        "every simulated HxD topology the mesh divides "
                        "into, plus the device-loss and host-loss drills "
                        "(bit-identical gate; gates measured DCN-leg byte "
                        "reduction and the survivors' (H',H') re-plan)")
    p.add_argument("--external-wave", action="store_true",
                   help="out-of-core wave-pipeline benchmark: sort a "
                        "dataset 8x the per-wave device budget through the "
                        "mesh wave pipeline (overlap-on vs overlap-off A/B "
                        "+ a mid-wave fault drill with run-granular "
                        "resume); JSON rows with over_hbm_factor and "
                        "resume_fraction")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "batch", help="sort many files as ONE batched SPMD program (dp axis)"
    )
    p.add_argument("inputs", nargs="+")
    p.add_argument("--outdir", required=True)
    p.add_argument("--dp", type=int,
                   help="independent-jobs mesh axis size (default from conf)")
    common(p)
    p.set_defaults(fn=cmd_batch)

    p = sub.add_parser("gen", help="generate synthetic input files")
    p.add_argument("n", type=int)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--dist", default="uniform", choices=["uniform", "zipf", "terasort"])
    p.add_argument("--dtype", default="int32")
    p.add_argument("--zipf-a", type=float, default=1.3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--format", default="text", choices=["text", "bin"],
                   help="'bin' streams raw binary keys (external-sort input)")
    p.set_defaults(fn=cmd_gen)

    p = sub.add_parser("terasort", help="sort a binary 100-byte-record file")
    p.add_argument("input")
    p.add_argument("-o", "--output")
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--external", action="store_true",
                   help="out-of-core: spill sorted record runs, native merge")
    p.add_argument("--mesh", type=int,
                   help="external mode: run record waves over this many "
                        "devices (the wave pipeline; conf EXTERNAL_MESH)")
    p.add_argument("--run-recs", type=int, default=1 << 20,
                   help="records per spilled run / per wave (external mode)")
    p.add_argument("--exchange",
                   choices=["alltoall", "ring", "fused", "hier"],
                   help="bucket exchange schedule (conf key EXCHANGE; flag "
                        "wins).  In-core record sorts route it through the "
                        "kv exchange plane; external record waves run the "
                        "host-side exchange, where mesh schedules warn and "
                        "the knob is validated for conf parity")
    p.add_argument("--hier-hosts", type=int,
                   help="host grouping for --exchange hier (default 0 = "
                        "auto; conf HIER_HOSTS)")
    p.add_argument("--spill-dir")
    p.add_argument("--job-id", default="tera_external")
    p.add_argument("--no-resume", action="store_true",
                   help="discard checkpointed runs and start fresh")
    p.add_argument("--journal",
                   help="write the job's structured event journal (JSONL) "
                        "here; render with `dsort report`")
    p.add_argument("--journal-rotate-mb", type=float,
                   help="rotate the journal to PATH.N at this size")
    p.add_argument("--memwatch", action="store_true",
                   help="snapshot device memory at phase boundaries into "
                        "hbm_watermark journal events")
    p.add_argument("--conf", help="KEY=value conf file (EXTERNAL_* keys)")
    p.set_defaults(fn=cmd_terasort)

    p = sub.add_parser("external", help="out-of-core sort of a raw binary key file")
    p.add_argument("input")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--dtype", default="int32")
    p.add_argument("--kernel", choices=["auto", "lax", "block", "bitonic", "pallas", "radix"])
    p.add_argument("--run-elems", type=int, default=None,
                   help="keys per spilled run, single-device mode (conf "
                        "EXTERNAL_RUN_ELEMS; default %d)" % (1 << 22))
    p.add_argument("--mesh", type=int,
                   help="sort in mesh-parallel WAVES over this many devices "
                        "(the wave pipeline, ARCHITECTURE §10; conf "
                        "EXTERNAL_MESH)")
    p.add_argument("--wave-elems", type=int, default=None,
                   help="keys per wave — the per-wave device budget (conf "
                        "EXTERNAL_WAVE_ELEMS; default %d)" % (1 << 22))
    p.add_argument("--no-overlap", action="store_true",
                   help="disable the wave pipeline's spill/exchange overlap "
                        "(the A/B baseline)")
    p.add_argument("--exchange", choices=["ring", "fused", "hier"],
                   help="per-wave exchange schedule (wave mode; default "
                        "ring; fused = exchange+merge as one Pallas kernel "
                        "per wave; hier = the two-level pod schedule — "
                        "each wave aggregates per destination HOST before "
                        "the DCN leg, ARCHITECTURE §17)")
    p.add_argument("--hier-hosts", type=int,
                   help="host grouping for --exchange hier (default 0 = "
                        "auto; conf HIER_HOSTS)")
    p.add_argument("--redundancy", type=int,
                   help="coded redundancy r for each wave's exchange "
                        "(default 1 = off): a device lost mid-wave repairs "
                        "from replica slots instead of a host re-sort — "
                        "wave_runs_resorted stays 0 (ARCHITECTURE §14)")
    p.add_argument("--redundancy-mode",
                   choices=["replicate", "parity"],
                   help="replica plane mode for coded waves: full copies "
                        "or XOR/P+Q parity slots (ARCHITECTURE §18)")
    p.add_argument("--spill-dir")
    p.add_argument("--job-id", default="external")
    p.add_argument("--no-resume", action="store_true",
                   help="discard checkpointed runs and start fresh")
    p.add_argument("--journal",
                   help="write the job's structured event journal (JSONL) "
                        "here; render with `dsort report` (--analyze shows "
                        "the wave waterfall)")
    p.add_argument("--journal-rotate-mb", type=float,
                   help="rotate the journal to PATH.N at this size")
    p.add_argument("--memwatch", action="store_true",
                   help="snapshot device memory at phase boundaries into "
                        "hbm_watermark journal events")
    p.add_argument("--conf", help="KEY=value conf file (EXTERNAL_* keys)")
    p.set_defaults(fn=cmd_external)

    p = sub.add_parser(
        "validate", help="validate a sort output (order + permutation checksum)"
    )
    p.add_argument("input")
    p.add_argument("--against", help="original input file to prove permutation")
    p.add_argument("--terasort", action="store_true",
                   help="treat files as binary 100-byte-record TeraSort data")
    p.add_argument("--binary", action="store_true",
                   help="treat files as raw binary key arrays (streamed)")
    p.add_argument("--dtype", default="int32")
    p.set_defaults(fn=cmd_validate)

    p = sub.add_parser(
        "report", help="render event journal(s) (timeline + phases/counters)"
    )
    p.add_argument("journal", nargs="+",
                   help="journal JSONL(s) from `--journal`; several merge "
                        "into one clock-aligned fleet timeline; a "
                        "directory or glob expands to the journals inside "
                        "(fleet runs write one per agent)")
    p.add_argument("--merge", action="store_true",
                   help="merge the journals into one aligned trace "
                        "(implied when more than one is given)")
    p.add_argument("--chrome-trace",
                   help="also export a Perfetto trace_event JSON here "
                        "(one pid per source journal, one tid per job)")
    p.add_argument("--analyze", action="store_true",
                   help="replay the journal(s) into a why-slow verdict: "
                        "phase waterfall + cross-process critical path, "
                        "straggler attribution, queue/compile/execute "
                        "split, wire bytes, skew, HBM watermarks")
    p.add_argument("--analyze-json",
                   help="also write the machine-readable verdict JSON here")
    p.add_argument("--link-mbps", type=float,
                   help="measured link bandwidth (Mbit/s): prices the "
                        "journal's wire bytes into expected seconds in "
                        "the --analyze verdict")
    p.add_argument("--conform", action="store_true",
                   help="replay the journal(s) against the declared "
                        "TRACE_CONTRACTS grammars (ARCHITECTURE §16) and "
                        "exit 1 on any violated contract")
    p.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "top", help="console view of running metrics endpoint(s) "
                    "(several URLs = the per-mesh fleet view)"
    )
    p.add_argument("url", nargs="*",
                   help="metrics endpoint URL(s) (default "
                        "http://127.0.0.1:9100/metrics; several render the "
                        "fleet view with combined admissions/cache tables)")
    p.add_argument("--interval", type=float,
                   help="refresh every N seconds (default: one-shot)")
    p.add_argument("--count", type=int,
                   help="stop after N refreshes (with --interval)")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "lint",
        help="project-native static analysis (registry/concurrency/tracing "
             "invariants; see ARCHITECTURE.md)",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to check (default: dsort_tpu/)")
    p.add_argument("--format", default="text",
                   choices=["text", "json", "sarif"],
                   help="output format (sarif: SARIF 2.1.0 for "
                        "code-scanning upload)")
    p.add_argument("--stats", action="store_true",
                   help="print a per-checker wall-time/findings table "
                        "(file vs project phase) to stderr")
    p.add_argument("--baseline",
                   help="baseline JSON path (default from [tool.dsort.lint])")
    p.add_argument("--write-baseline", action="store_true",
                   help="record the current findings as tolerated (the "
                        "shipped tree keeps this file empty)")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs git HEAD (plus "
                        "untracked) — the interactive pre-commit scope")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the content-hash result cache "
                        "(.lint-cache.json)")
    p.add_argument("--root",
                   help="project root (default: nearest pyproject.toml)")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "spec",
        help="protocol spec plane: explicit-state model check of the fleet "
             "protocol, or replay of a violation fixture (ARCHITECTURE §16)",
    )
    p.add_argument("action", choices=["check", "replay"],
                   help="check: explore bounded interleavings against the "
                        "invariant catalog; replay: re-execute a dumped "
                        "violation fixture deterministically")
    p.add_argument("--fixture", help="fixture JSON for `replay`")
    p.add_argument("--agents", type=int, default=2,
                   help="modeled fleet size (default 2)")
    p.add_argument("--jobs", type=int, default=3,
                   help="jobs submitted in the model (default 3)")
    p.add_argument("--duplications", type=int, default=1,
                   help="frame retransmission budget (default 1)")
    p.add_argument("--deaths", type=int, default=1,
                   help="link-death budget (default 1)")
    p.add_argument("--crashes", type=int, default=1,
                   help="controller crash+restore budget (default 1)")
    p.add_argument("--max-states", type=int, default=12_000,
                   help="distinct-state exploration bound (default 12000 — "
                        "the make spec-smoke bound)")
    p.add_argument("--max-depth", type=int, default=40,
                   help="schedule depth bound (default 40)")
    p.add_argument("--seam", action="append",
                   choices=["ack_before_persist", "nonatomic_reserve"],
                   help="re-introduce a known-bad mutation (test seam); "
                        "repeatable — the checker must find a violation")
    p.add_argument("--dump-fixture",
                   help="write the minimized violating schedule as a "
                        "replayable JSON fixture here")
    p.set_defaults(fn=cmd_spec)

    p = sub.add_parser("coordinator", help="native TCP coordinator + job REPL")
    common(p)  # provides --workers (cluster size; default 4 below)
    p.add_argument("--port", type=int)
    p.add_argument("--join-timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_coordinator)

    p = sub.add_parser("worker", help="worker shim (joins a coordinator)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9008)
    p.add_argument("--conf")
    p.add_argument("--dtype", default="int32")
    p.add_argument("--backend", choices=["jax", "numpy"], default="jax")
    p.add_argument("--kernel", default="auto",
                   choices=["auto", "lax", "block", "bitonic", "pallas", "radix"])
    p.set_defaults(fn=None)

    args = ap.parse_args(argv)
    if args.cmd not in ("lint", "spec"):
        # 64-bit keys (int64/uint64 — BASELINE config #3, TeraSort prefixes)
        # need x64 mode before any backend use; the library is tested under
        # x64 (tests/conftest.py), so enable it for every execution command.
        # Routed through the compat shim (the one allowed call site — the
        # analysis suite's DS501 enforces this); `lint` and `spec` skip
        # the toggle so static analysis and model checking never
        # initialize a backend.
        from dsort_tpu.utils.compat import set_x64

        set_x64(True)
    if args.cmd == "worker":
        from dsort_tpu.runtime.worker import main as worker_main

        wargs = ["--host", args.host, "--port", str(args.port),
                 "--dtype", args.dtype, "--backend", args.backend,
                 "--kernel", args.kernel]
        if args.conf:
            wargs += ["--conf", args.conf]
        return worker_main(wargs)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
