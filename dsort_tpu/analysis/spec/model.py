"""Explicit-state model checker for the fleet protocol (`dsort spec check`).

The controller's job table, the agent's job/done stores, and the real
`ControlPolicy` are backend-free and `state_dict()`-serializable — which
is exactly what explicit-state exploration needs.  `FleetModel` closes
the loop: a bounded abstract fleet (N agents, J jobs, frame multisets
for the two wire directions) whose controller queue IS a live
`serve.policy.ControlPolicy` (round-tripped through `state_dict` at
every step, so DRR token conservation is checked against the real
accounting code, not a model of it), explored breadth-first over every
enabled interleaving of:

- frame delivery in any order (the wire multiset makes reordering
  inherent), bounded frame duplication (TCP retry / re-attach races),
- agent death (dropping its in-flight frames) and re-attach (resending
  its held results — the restart contract's duplicate source),
- controller crash + restore from the durable snapshot, with the
  real reconcile semantics (done -> finish, running -> keep, unknown ->
  requeue), and the crash points BETWEEN persist and ack that PR 12's
  review rounds kept finding bugs in.

Every reached state is checked against the `SPEC_INVARIANTS` catalog
(machines.py).  A violating schedule is shrunk by greedy delta-debugging
to a minimal action list and dumped as a JSON fixture that
`replay_schedule` re-executes deterministically — the fixtures under
`tests/data/spec/` are exactly such dumps.

``seams`` re-introduce two real bugs the PR 12 reviews fixed, behind
test-only flags, so the checker is never green-by-construction
(tests/test_spec.py asserts both are caught):

- ``ack_before_persist``: the result handler sends ``result_ack`` before
  the durable flush (the dropped fsync-before-ack ordering).
- ``nonatomic_reserve``: the agent's duplicate-jid check and reservation
  are two steps instead of one atomic critical section, so duplicate
  submits interleave into a double execution.

Stdlib-only at import time (analysis-layer contract); `ControlPolicy`
is imported lazily inside the model because `serve.policy` uses numpy.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field

from dsort_tpu.analysis.spec.machines import SPEC_INVARIANTS

#: The supported test-only bug seams (see module docstring).
SEAMS = ("ack_before_persist", "nonatomic_reserve")


@dataclass(frozen=True)
class ModelConfig:
    """Bounds for one exploration.  The defaults are the smoke bound:
    big enough to clear 10k distinct states, small enough for seconds."""

    n_agents: int = 2
    n_jobs: int = 3
    outstanding_cap: int = 2
    max_duplications: int = 1
    max_deaths: int = 1
    max_reattaches: int = 1
    max_crashes: int = 1
    max_requeues: int = 3

    def to_dict(self) -> dict:
        return {
            "n_agents": self.n_agents, "n_jobs": self.n_jobs,
            "outstanding_cap": self.outstanding_cap,
            "max_duplications": self.max_duplications,
            "max_deaths": self.max_deaths,
            "max_reattaches": self.max_reattaches,
            "max_crashes": self.max_crashes,
            "max_requeues": self.max_requeues,
        }


@dataclass
class Violation:
    invariant: str
    detail: str
    schedule: list[str]

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail,
                "schedule": list(self.schedule)}


@dataclass
class CheckResult:
    states: int
    transitions: int
    depth: int
    elapsed_s: float
    truncated: bool
    violation: Violation | None = None
    invariants: tuple[str, ...] = field(
        default_factory=lambda: tuple(SPEC_INVARIANTS)
    )

    @property
    def ok(self) -> bool:
        return self.violation is None


def _policy(state_dict=None):
    """A fresh real ControlPolicy, optionally restored.  Lazy import:
    serve.policy uses numpy, which the analysis layer must not load at
    module import time (DS601)."""
    from dsort_tpu.serve.policy import ControlPolicy

    p = ControlPolicy(max_queue_depth=64, max_tenant_inflight=16)
    if state_dict is not None:
        p.load_state(json.loads(json.dumps(state_dict)))
    return p


def _drr_tokens(policy_state: dict) -> list[str]:
    """Every queued token inside a ControlPolicy state_dict — the ground
    truth for queue conservation."""
    tokens = []
    drr = policy_state.get("drr", {})
    for _, entries in sorted(dict(drr.get("queues", {})).items()):
        for entry in entries:
            # entry shape: (cost, token) or {"token": ...} — take the
            # token wherever the DRR serialization put it.
            if isinstance(entry, dict):
                tokens.append(str(entry.get("token")))
            elif isinstance(entry, (list, tuple)) and len(entry) >= 2:
                tokens.append(str(entry[1]))
            else:
                tokens.append(str(entry))
    return tokens


class FleetModel:
    """One abstract fleet; states are plain JSON-able dicts."""

    def __init__(self, config: ModelConfig | None = None,
                 seams: tuple[str, ...] = ()):
        bad = set(seams) - set(SEAMS)
        if bad:
            raise ValueError(f"unknown seam(s) {sorted(bad)}; know {SEAMS}")
        self.config = config or ModelConfig()
        self.seams = tuple(seams)

    # -- state ---------------------------------------------------------------

    def initial_state(self) -> dict:
        cfg = self.config
        pol = _policy()
        return {
            "ctl": {
                "jobs": {},          # jid -> {status, agent, readmits}
                "policy": pol.state_dict(),
                "pending_flush": False,   # seam: finish happened, durable stale
                "durable": {"jobs": {}, "policy": pol.state_dict()},
            },
            "agents": {
                f"a{i}": {"alive": True, "jobs": {}, "done": [],
                          "pending": []}
                for i in range(cfg.n_agents)
            },
            # Per-link FIFO queues, one per direction — TCP's per-
            # connection ordering, exactly.  Reordering still arises the
            # ways it really can: across links, across directions, and
            # across link incarnations (death drops the queue, re-attach
            # resends held results).
            "net": {
                "c2a": {f"a{i}": [] for i in range(cfg.n_agents)},
                "a2c": {f"a{i}": [] for i in range(cfg.n_agents)},
            },
            "submitted": [],
            "runs": {},              # "aid/jid" -> count
            "finishes": {},          # jid -> count
            "budget": {"dup": cfg.max_duplications,
                       "deaths": cfg.max_deaths,
                       "reattaches": cfg.max_reattaches,
                       "crashes": cfg.max_crashes},
        }

    @staticmethod
    def canon(state: dict) -> str:
        return json.dumps(state, sort_keys=True, separators=(",", ":"))

    @staticmethod
    def _copy(state: dict) -> dict:
        return json.loads(json.dumps(state))

    # -- invariants ----------------------------------------------------------

    def check_invariants(self, state: dict) -> tuple[str, str] | None:
        """First violated (invariant, detail) or None."""
        ctl = state["ctl"]
        jobs = ctl["jobs"]
        for jid in state["submitted"]:
            if jid not in jobs:
                return ("no_lost_job",
                        f"submitted job {jid} missing from the table")
        for jid, n in state["finishes"].items():
            if n > 1:
                return ("no_double_finish",
                        f"job {jid} finished {n} times")
        for aid, frames in state["net"]["c2a"].items():
            for fr in frames:
                if fr[0] != "result_ack":
                    continue
                jid = fr[1]
                dur = ctl["durable"]["jobs"].get(jid, {})
                if dur.get("status") not in ("done", "failed"):
                    return (
                        "durable_before_ack",
                        f"result_ack for {jid} on the wire to {aid} while "
                        f"durable status is {dur.get('status')!r}",
                    )
        for key, n in state["runs"].items():
            if n > 1:
                return ("no_double_run",
                        f"{key} executed {n} times on one agent")
        cap = self.config.outstanding_cap
        held: dict[str, int] = {}
        for jid, j in jobs.items():
            if j["status"] in ("dispatching", "inflight") and j["agent"]:
                held[j["agent"]] = held.get(j["agent"], 0) + 1
        for aid, n in held.items():
            if n > cap:
                return ("bounded_outstanding",
                        f"agent {aid} holds {n} jobs (cap {cap})")
        # Lazy conservation, exactly the real dispatcher's discipline:
        # every queued job holds exactly one DRR token; a token for a
        # non-queued job is legal ONLY when that job is terminal (a
        # stale token the pop site will discard).
        tokens = _drr_tokens(ctl["policy"])
        for jid, j in jobs.items():
            if j["status"] == "queued" and tokens.count(jid) != 1:
                return ("queue_conservation",
                        f"queued job {jid} holds {tokens.count(jid)} DRR "
                        f"tokens (want exactly 1)")
        for tok in tokens:
            j = jobs.get(tok)
            if j is None:
                return ("queue_conservation",
                        f"DRR token {tok} names no known job")
            if j["status"] in ("dispatching", "inflight"):
                return ("queue_conservation",
                        f"DRR token {tok} for a job already {j['status']}")
        return None

    # -- actions -------------------------------------------------------------

    def enabled_actions(self, state: dict) -> list[str]:
        cfg = self.config
        ctl = state["ctl"]
        acts = []
        n_sub = len(state["submitted"])
        if n_sub < cfg.n_jobs:
            acts.append(f"submit:j{n_sub}")  # in-order: collapses symmetry
        queued = any(
            j["status"] == "queued" for j in ctl["jobs"].values()
        )
        if queued:
            held: dict[str, int] = {}
            for j in ctl["jobs"].values():
                if j["status"] in ("dispatching", "inflight") and j["agent"]:
                    held[j["agent"]] = held.get(j["agent"], 0) + 1
            for aid, ag in state["agents"].items():
                if ag["alive"] and held.get(aid, 0) < cfg.outstanding_cap:
                    acts.append(f"dispatch:{aid}")
        for aid, frames in state["net"]["c2a"].items():
            if frames:  # FIFO: only the head is deliverable
                acts.append(f"deliver_c2a:{aid}:{':'.join(frames[0])}")
        for aid, frames in state["net"]["a2c"].items():
            if frames:
                acts.append(f"deliver_a2c:{aid}:{':'.join(frames[0])}")
        if state["budget"]["dup"] > 0:
            for chan in ("c2a", "a2c"):
                for aid, frames in state["net"][chan].items():
                    if frames:  # retransmit: a fresh copy at the tail
                        acts.append(f"dup:{chan}:{aid}:{':'.join(frames[0])}")
        for jid, j in sorted(ctl["jobs"].items()):
            # The dispatch lane's accept timeout: reroute while the
            # original submit may still be in flight — the real
            # application-level duplicate source.
            if j["status"] == "dispatching":
                acts.append(f"timeout:{j['agent']}:{jid}")
        for aid, ag in state["agents"].items():
            if "nonatomic_reserve" in self.seams:
                for jid in ag["pending"]:
                    acts.append(f"reserve:{aid}:{jid}")
            for jid, st in ag["jobs"].items():
                if st == "running":
                    acts.append(f"run:{aid}:{jid}")
            if ag["alive"] and state["budget"]["deaths"] > 0:
                acts.append(f"die:{aid}")
            if not ag["alive"] and state["budget"]["reattaches"] > 0:
                acts.append(f"reattach:{aid}")
            if not ag["alive"] and any(
                j["status"] in ("dispatching", "inflight")
                and j["agent"] == aid
                for j in ctl["jobs"].values()
            ):
                acts.append(f"detect_death:{aid}")
        if ctl["pending_flush"]:
            acts.append("flush")
        if state["budget"]["crashes"] > 0 and state["submitted"]:
            acts.append("crash")
        return acts

    def apply(self, state: dict, action: str) -> dict | None:
        """The action's successor state, or None when it is not enabled
        in ``state`` (replay of a shrunk schedule hits this)."""
        s = self._copy(state)
        parts = action.split(":")
        kind = parts[0]
        if kind == "submit":
            return self._submit(s, parts[1])
        if kind == "dispatch":
            return self._dispatch(s, parts[1])
        if kind == "deliver_c2a":
            return self._deliver_c2a(s, parts[1], tuple(parts[2:]))
        if kind == "deliver_a2c":
            return self._deliver_a2c(s, parts[1], tuple(parts[2:]))
        if kind == "dup":
            return self._dup(s, parts[1], parts[2], tuple(parts[3:]))
        if kind == "timeout":
            return self._timeout(s, parts[1], parts[2])
        if kind == "reserve":
            return self._reserve(s, parts[1], parts[2])
        if kind == "run":
            return self._run(s, parts[1], parts[2])
        if kind == "die":
            return self._die(s, parts[1])
        if kind == "reattach":
            return self._reattach(s, parts[1])
        if kind == "detect_death":
            return self._detect_death(s, parts[1])
        if kind == "flush":
            return self._flush(s)
        if kind == "crash":
            return self._crash(s)
        raise ValueError(f"unknown action {action!r}")

    # -- controller-side steps ----------------------------------------------

    def _persist(self, s: dict) -> None:
        """_persist_locked + _flush_persist: snapshot jobs (dispatching
        persists as inflight, exactly like `_Job.state()`) and the live
        policy into the durable half."""
        jobs = {}
        for jid, j in s["ctl"]["jobs"].items():
            st = "inflight" if j["status"] == "dispatching" else j["status"]
            jobs[jid] = {"status": st, "agent": j["agent"],
                         "readmits": j["readmits"]}
        s["ctl"]["durable"] = {"jobs": jobs, "policy": s["ctl"]["policy"]}
        s["ctl"]["pending_flush"] = False

    def _submit(self, s: dict, jid: str) -> dict | None:
        if jid in s["ctl"]["jobs"]:
            return None
        pol = _policy(s["ctl"]["policy"])
        verdict = pol.consider("t")
        if not verdict.admitted:
            return None
        pol.push("t", 1, jid)
        s["ctl"]["policy"] = pol.state_dict()
        s["ctl"]["jobs"][jid] = {
            "status": "queued", "agent": None, "readmits": 0,
        }
        s["submitted"].append(jid)
        self._persist(s)
        return s

    def _dispatch(self, s: dict, aid: str) -> dict | None:
        ag = s["agents"].get(aid)
        if ag is None or not ag["alive"]:
            return None
        pol = _policy(s["ctl"]["policy"])
        nxt = pol.pop()
        if nxt is None:
            return None
        _, jid = nxt
        jid = str(jid)
        s["ctl"]["policy"] = pol.state_dict()
        job = s["ctl"]["jobs"].get(jid)
        if job is None or job["status"] != "queued":
            # Stale token (the job finished while requeued): the real
            # dispatcher consumes and discards it (`continue` at the
            # pop site) — lazy conservation, checked as such.
            return s
        job["status"] = "dispatching"
        job["agent"] = aid
        self._persist(s)  # persisted BEFORE the frame leaves
        self._enqueue(s, "c2a", aid, ("submit", jid))
        return s

    def _enqueue(self, s: dict, chan: str, aid: str, frame: tuple) -> None:
        s["net"][chan][aid].append(list(frame))

    def _take(self, s: dict, chan: str, aid: str, frame: tuple) -> bool:
        """Pop the FIFO head iff it matches ``frame`` (replay of a stale
        schedule fails the match and the action reports not-enabled)."""
        q = s["net"][chan][aid]
        if not q or q[0] != list(frame):
            return False
        q.pop(0)
        return True

    def _deliver_a2c(self, s: dict, aid: str, frame: tuple) -> dict | None:
        if not self._take(s, "a2c", aid, frame):
            return None
        kind, jid = frame[0], frame[1]
        job = s["ctl"]["jobs"].get(jid)
        if kind == "accepted":
            # _dispatch_one's accept path: only a still-dispatching job
            # transitions; anything else is late and ignored.
            if job is not None and job["status"] == "dispatching" \
                    and job["agent"] == aid:
                job["status"] = "inflight"
                self._persist(s)
            return s
        if kind == "result":
            if job is None or job["status"] in ("done", "failed"):
                # late duplicate: re-ack, never re-finish (_on_result)
                self._enqueue(s, "c2a", aid, ("result_ack", jid))
                return s
            if job["status"] not in ("inflight", "dispatching"):
                # result for a re-queued job (requeue raced the wire):
                # the real controller would also just re-ack after
                # _finish_* sees a non-terminal... mirror _on_result: a
                # queued job is NOT finished-elsewhere, so it finishes
                # here (the dispatch that re-queued it will find the
                # done status and stand down).
                pass
            # _finish_ok: terminal in memory, policy accounting, persist.
            job["status"] = "done"
            job["agent"] = None
            pol = _policy(s["ctl"]["policy"])
            pol.finished("t")
            s["ctl"]["policy"] = pol.state_dict()
            s["finishes"][jid] = s["finishes"].get(jid, 0) + 1
            if "ack_before_persist" in self.seams:
                # THE SEAM: the ack leaves before the durable flush.
                self._enqueue(s, "c2a", aid, ("result_ack", jid))
                s["ctl"]["pending_flush"] = True
            else:
                self._persist(s)
                self._enqueue(s, "c2a", aid, ("result_ack", jid))
            return s
        raise ValueError(f"unexpected a2c frame {frame!r}")

    def _flush(self, s: dict) -> dict | None:
        if not s["ctl"]["pending_flush"]:
            return None
        self._persist(s)
        return s

    def _timeout(self, s: dict, aid: str, jid: str) -> dict | None:
        """_dispatch_one's accept timeout: reroute a dispatching job
        while its submit frame may still be in flight on the old lane —
        the application-level duplicate-submit source the agent's atomic
        reservation exists to survive."""
        job = s["ctl"]["jobs"].get(jid)
        if job is None or job["status"] != "dispatching" \
                or job["agent"] != aid:
            return None
        if job["readmits"] >= self.config.max_requeues:
            job["status"] = "failed"
            job["agent"] = None
            pol = _policy(s["ctl"]["policy"])
            pol.finished("t")
            s["ctl"]["policy"] = pol.state_dict()
            s["finishes"][jid] = s["finishes"].get(jid, 0) + 1
        else:
            job["status"] = "queued"
            job["agent"] = None
            job["readmits"] += 1
            pol = _policy(s["ctl"]["policy"])
            pol.requeue("t", 1, jid)
            s["ctl"]["policy"] = pol.state_dict()
        self._persist(s)
        return s

    def _detect_death(self, s: dict, aid: str) -> dict | None:
        ag = s["agents"].get(aid)
        if ag is None or ag["alive"]:
            return None
        hit = False
        for jid, job in sorted(s["ctl"]["jobs"].items()):
            if job["agent"] == aid and job["status"] in (
                "dispatching", "inflight",
            ):
                if job["readmits"] >= self.config.max_requeues:
                    job["status"] = "failed"
                    job["agent"] = None
                    pol = _policy(s["ctl"]["policy"])
                    pol.finished("t")
                    s["ctl"]["policy"] = pol.state_dict()
                    s["finishes"][jid] = s["finishes"].get(jid, 0) + 1
                else:
                    job["status"] = "queued"
                    job["agent"] = None
                    job["readmits"] += 1
                    pol = _policy(s["ctl"]["policy"])
                    pol.requeue("t", 1, jid)
                    s["ctl"]["policy"] = pol.state_dict()
                hit = True
        if not hit:
            return None
        self._persist(s)
        return s

    def _crash(self, s: dict) -> dict | None:
        if s["budget"]["crashes"] <= 0:
            return None
        s["budget"]["crashes"] -= 1
        # The wire dies with the process; both directions drop.
        for chan in ("c2a", "a2c"):
            for aid in s["net"][chan]:
                s["net"][chan][aid] = []
        # _load_state: memory := durable.
        dur = self._copy(s["ctl"]["durable"])
        s["ctl"]["jobs"] = dur["jobs"]
        s["ctl"]["policy"] = dur["policy"]
        s["ctl"]["pending_flush"] = False
        # _reconcile_restore: ask every agent about inflight jobs.
        for jid, job in sorted(s["ctl"]["jobs"].items()):
            if job["status"] != "inflight":
                continue
            aid = job["agent"]
            ag = s["agents"].get(aid) if aid else None
            done = [d[0] for d in ag["done"]] if ag else []
            if ag is not None and ag["alive"] and jid in done:
                job["status"] = "done"
                job["agent"] = None
                pol = _policy(s["ctl"]["policy"])
                pol.finished("t")
                s["ctl"]["policy"] = pol.state_dict()
                s["finishes"][jid] = s["finishes"].get(jid, 0) + 1
                self._enqueue(s, "c2a", aid, ("result_ack", jid))
            elif ag is not None and ag["alive"] and jid in ag["jobs"]:
                pass  # still running: stays inflight
            else:
                # unknown to its agent (or the agent is gone): requeue.
                job["status"] = "queued"
                job["agent"] = None
                job["readmits"] += 1
                pol = _policy(s["ctl"]["policy"])
                pol.requeue("t", 1, jid)
                s["ctl"]["policy"] = pol.state_dict()
        self._persist(s)
        return s

    # -- agent-side steps ----------------------------------------------------

    def _deliver_c2a(self, s: dict, aid: str, frame: tuple) -> dict | None:
        if not self._take(s, "c2a", aid, frame):
            return None
        ag = s["agents"][aid]
        if not ag["alive"]:
            return s  # dropped on the floor: the connection is gone
        kind, jid = frame[0], frame[1]
        if kind == "submit":
            done = [d[0] for d in ag["done"]]
            if "nonatomic_reserve" in self.seams:
                # THE SEAM: duplicate check now, reservation later — two
                # deliveries both pass the check before either reserves.
                if jid in ag["jobs"] or jid in done:
                    self._enqueue(s, "a2c", aid, ("accepted", jid))
                    if jid in done:
                        self._enqueue(s, "a2c", aid, ("result", jid))
                    return s
                ag["pending"].append(jid)
                ag["pending"].sort()
                return s
            # Real code: check AND reserve atomically under _lock.
            if jid in ag["jobs"] or jid in done or jid in ag["pending"]:
                self._enqueue(s, "a2c", aid, ("accepted", jid))
                if jid in done:
                    self._enqueue(s, "a2c", aid, ("result", jid))
                return s
            ag["jobs"][jid] = "running"
            key = f"{aid}/{jid}"
            s["runs"][key] = s["runs"].get(key, 0) + 1
            self._enqueue(s, "a2c", aid, ("accepted", jid))
            return s
        if kind == "result_ack":
            ag["done"] = [d for d in ag["done"] if d[0] != jid]
            return s
        raise ValueError(f"unexpected c2a frame {frame!r}")

    def _reserve(self, s: dict, aid: str, jid: str) -> dict | None:
        ag = s["agents"][aid]
        if jid not in ag["pending"]:
            return None
        ag["pending"].remove(jid)
        # The seam's point: no re-check against jobs/done here.
        ag["jobs"][jid] = "running"
        key = f"{aid}/{jid}"
        s["runs"][key] = s["runs"].get(key, 0) + 1
        if ag["alive"]:
            self._enqueue(s, "a2c", aid, ("accepted", jid))
        return s

    def _run(self, s: dict, aid: str, jid: str) -> dict | None:
        ag = s["agents"][aid]
        if ag["jobs"].get(jid) != "running":
            return None
        del ag["jobs"][jid]
        ag["done"].append([jid, True])
        ag["done"].sort()
        if ag["alive"]:
            self._enqueue(s, "a2c", aid, ("result", jid))
        # else: the push fails on the dead link; the done store holds the
        # result and the next hello resends it (the restart contract).
        return s

    def _die(self, s: dict, aid: str) -> dict | None:
        """Link death: the agent PROCESS survives (running work keeps
        running, the done store keeps its held results), but both wire
        directions drop their in-flight frames."""
        ag = s["agents"][aid]
        if not ag["alive"] or s["budget"]["deaths"] <= 0:
            return None
        s["budget"]["deaths"] -= 1
        ag["alive"] = False
        s["net"]["c2a"][aid] = []
        s["net"]["a2c"][aid] = []
        return s

    def _reattach(self, s: dict, aid: str) -> dict | None:
        ag = s["agents"][aid]
        if ag["alive"] or s["budget"]["reattaches"] <= 0:
            return None
        s["budget"]["reattaches"] -= 1
        ag["alive"] = True
        # hello/welcome: held results resend (the duplicate source).
        for jid, _ok in ag["done"]:
            self._enqueue(s, "a2c", aid, ("result", jid))
        return s

    def _dup(self, s: dict, chan: str, aid: str, frame: tuple) -> dict | None:
        q = s["net"][chan][aid]
        if s["budget"]["dup"] <= 0 or not q or q[0] != list(frame):
            return None
        s["budget"]["dup"] -= 1
        self._enqueue(s, chan, aid, frame)
        return s


# -- exploration -------------------------------------------------------------


def check_model(
    config: ModelConfig | None = None,
    seams: tuple[str, ...] = (),
    max_states: int = 200_000,
    max_depth: int = 40,
    stop_on_violation: bool = True,
) -> CheckResult:
    """Breadth-first exploration with canonical-state dedup."""
    model = FleetModel(config, seams)
    t0 = time.monotonic()
    init = model.initial_state()
    init_key = model.canon(init)
    seen = {init_key}
    parents: dict[str, tuple[str | None, str | None]] = {
        init_key: (None, None)
    }
    frontier = deque([(init, 0)])
    transitions = 0
    depth_seen = 0
    truncated = False
    violation = None

    def path_to(key: str) -> list[str]:
        acts = []
        while True:
            parent, act = parents[key]
            if parent is None:
                break
            acts.append(act)
            key = parent
        return list(reversed(acts))

    bad = model.check_invariants(init)
    if bad is not None:
        violation = Violation(bad[0], bad[1], [])
    while frontier and violation is None:
        state, depth = frontier.popleft()
        if depth >= max_depth:
            truncated = True
            continue
        key = model.canon(state)
        for action in model.enabled_actions(state):
            nxt = model.apply(state, action)
            if nxt is None:
                continue
            transitions += 1
            nkey = model.canon(nxt)
            if nkey in seen:
                continue
            seen.add(nkey)
            parents[nkey] = (key, action)
            depth_seen = max(depth_seen, depth + 1)
            bad = model.check_invariants(nxt)
            if bad is not None:
                violation = Violation(bad[0], bad[1], path_to(nkey))
                if stop_on_violation:
                    break
            if len(seen) >= max_states:
                truncated = True
                break
            frontier.append((nxt, depth + 1))
        if truncated and len(seen) >= max_states:
            break
    if violation is not None:
        violation.schedule = minimize_schedule(
            model, violation.schedule, violation.invariant
        )
    return CheckResult(
        states=len(seen), transitions=transitions, depth=depth_seen,
        elapsed_s=round(time.monotonic() - t0, 3), truncated=truncated,
        violation=violation,
    )


def _schedule_violates(model: FleetModel, schedule: list[str],
                       invariant: str) -> bool:
    state = model.initial_state()
    for action in schedule:
        state = model.apply(state, action)
        if state is None:
            return False
        bad = model.check_invariants(state)
        if bad is not None:
            return bad[0] == invariant
    return False


def minimize_schedule(model: FleetModel, schedule: list[str],
                      invariant: str) -> list[str]:
    """Greedy delta-debug: drop any action whose removal still violates
    the same invariant, to a local fixpoint.  Deterministic."""
    sched = list(schedule)
    changed = True
    while changed:
        changed = False
        for i in range(len(sched)):
            cand = sched[:i] + sched[i + 1:]
            if _schedule_violates(model, cand, invariant):
                sched = cand
                changed = True
                break
    return sched


def replay_schedule(
    schedule: list[str],
    config: ModelConfig | None = None,
    seams: tuple[str, ...] = (),
) -> Violation | None:
    """Deterministically re-execute a schedule; the first invariant
    violation (or None).  This is the fixture-replay contract: a dumped
    fixture must reproduce its violation bit-for-bit."""
    model = FleetModel(config, seams)
    state = model.initial_state()
    applied = []
    for action in schedule:
        nxt = model.apply(state, action)
        if nxt is None:
            raise ValueError(
                f"schedule action {action!r} not enabled after {applied}"
            )
        applied.append(action)
        state = nxt
        bad = model.check_invariants(state)
        if bad is not None:
            return Violation(bad[0], bad[1], applied)
    return None


def dump_fixture(path: str, violation: Violation,
                 config: ModelConfig | None = None,
                 seams: tuple[str, ...] = ()) -> None:
    """A violating schedule as a replayable JSON fixture."""
    cfg = config or ModelConfig()
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "invariant": violation.invariant,
            "detail": violation.detail,
            "schedule": violation.schedule,
            "seams": list(seams),
            "config": cfg.to_dict(),
        }, f, indent=1)
        f.write("\n")


def load_fixture(path: str) -> tuple[list[str], ModelConfig, tuple[str, ...]]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return (
        list(data["schedule"]),
        ModelConfig(**data.get("config", {})),
        tuple(data.get("seams", ())),
    )


def format_result(result: CheckResult, seams: tuple[str, ...] = ()) -> str:
    lines = [
        f"spec check: {result.states:,} distinct states, "
        f"{result.transitions:,} transitions, depth {result.depth}, "
        f"{result.elapsed_s:.2f}s"
        + (" (stopped at first violation)" if not result.ok
           else " (bound reached)" if result.truncated else " (exhausted)")
        + (f" [seams: {', '.join(seams)}]" if seams else ""),
    ]
    lines.append(
        "invariants: " + ", ".join(SPEC_INVARIANTS)
    )
    if result.ok:
        lines.append("OK — no invariant violated in the explored space")
    else:
        v = result.violation
        lines.append(f"VIOLATION of {v.invariant}: {v.detail}")
        lines.append("minimized schedule:")
        for a in v.schedule:
            lines.append(f"  {a}")
    return "\n".join(lines) + "\n"
