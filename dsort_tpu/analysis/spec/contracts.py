"""Journal trace contracts: the declared grammar the drill journals obey.

Before this module, every fault drill pinned its recovery path with a
hand-rolled sequence literal (test_serve's eviction drill, test_coded's
death→re-form→reconstruct ordering, test_fleet's restore-before-dispatch
check) — one interleaving each, duplicated across the test tree, and
silently stale the moment an emission site moved.  `TRACE_CONTRACTS`
declares those sequences ONCE as a grammar over `utils.events.EVENT_TYPES`
names; the engine here replays any journal against it
(`dsort report --conform`, the analyzer's `conformance` verdict, and
`assert_conformant` in tests), and the DS11xx lint family keeps the
registry honest both ways: every `.event(...)` emission site belongs to a
declared contract (or is explicitly exempt), and every name a contract
mentions resolves against `EVENT_TYPES`.

Grammar: each contract is ``{"scope": (...), "when": (...), "steps":
(...)}``.  ``steps`` joins into one regular expression over event names —
tokens are event names plus ``( ) | ? * +`` — matched against the scoped
trace: records grouped by ``(src, *scope-fields)``, filtered to the
contract's alphabet (the set of names the steps mention), in journal
order.  ``when`` gates applicability: a trace is only checked when it
contains at least one trigger event, so an agent-side journal (which
never admits) is not held to the admission prefix.  The whole registry is
a PURE dict literal — the lint checker reads it by parsing this source,
never importing it, the same discipline as every other registry.
"""

from __future__ import annotations

import re

#: The declared trace grammars (pure literal: parsed, not imported, by
#: the DS11xx checker).  Names are contract ids surfaced in violations.
TRACE_CONTRACTS = {
    # The whole client-visible life of one job, serve-layer and
    # fleet-controller alike (both stamp every event with the ticket's
    # process-wide ``job`` ordinal): one admission verdict, dequeue/
    # attempt rounds with eviction-readmission or reroute loops between
    # them, at most one terminal, nothing after it.  `job_start` marks
    # "entered a scheduler" and legally repeats per layer: serve stamps
    # one at admission, the execution scheduler another after dequeue.
    # This is the grammar the PR-8 eviction drill's hand literal
    # unrolled one cycle of.
    "job_lifecycle": {
        "scope": ("job",),
        "when": ("job_admitted", "job_rejected"),
        "steps": (
            "( job_rejected",
            "| job_admitted job_start?",
            "  ( job_dequeued job_start? attempt_start* job_routed?",
            "    ( job_evicted job_readmitted | job_rerouted )? )*",
            "  ( result_fetch* job_done result_fetch* | job_failed )?",
            ")",
        ),
    },
    # The §14 failure-posture ordering: every coded reconstruction is
    # preceded by its trigger — the device death and mesh re-form on the
    # SPMD path, or the eviction-readmission pair on the serve path
    # (serve journals the loss as `job_evicted`, not `worker_dead`).
    # Extra trigger pairs without a reconstruct are the re-run posture
    # and legal in the same journal.  The free-standing alternative is
    # the wave pipeline's inline reconstruct (§18): a coded wave loss
    # never re-forms the mesh or evicts the job — the wave completes
    # from the replica/retained plane and the pipeline moves on.
    "coded_recovery": {
        "scope": (),
        "when": ("coded_recover",),
        "steps": (
            "( worker_dead mesh_reform coded_recover?",
            "| job_evicted job_readmitted coded_recover?",
            "| coded_recover )+",
        ),
    },
    # The §18 parity twin of `coded_recovery`: a parity reconstruction
    # follows the same trigger shapes — device death + mesh re-form on
    # the SPMD path, evict + readmit on the serve path — plus one more:
    # the wave pipeline journals its reconstruct INLINE (the mesh
    # survives a coded wave loss; nothing re-forms and nothing is
    # evicted), so a free-standing `parity_recover` is the third legal
    # shape there.
    "parity_recovery": {
        "scope": (),
        "when": ("parity_recover",),
        "steps": (
            "( worker_dead mesh_reform parity_recover?",
            "| job_evicted job_readmitted parity_recover?",
            "| parity_recover )+",
        ),
    },
    # The §18 straggler-first serve: per (job, range), the exactly-once
    # claim means at most ONE `coded_straggler_serve` ever lands, with
    # the racing owner leg's `coded_owner_fetch` on either side of it
    # (the owner thread finishes before or after the holder — both
    # legal; `won` says who took the claim).  An owner-win race journals
    # only the fetch and is not checked (the `when` gate), matching the
    # no-serve outcome.
    "straggler_serve": {
        "scope": ("job", "range"),
        "when": ("coded_straggler_serve",),
        "steps": (
            "coded_owner_fetch? coded_straggler_serve coded_owner_fetch?",
        ),
    },
    # The PR-12 restart contract, trace-side: a restarted controller
    # announces `controller_restore` BEFORE it dequeues or routes
    # anything — dispatch from a half-restored table is exactly the bug
    # class the drill exists to catch.
    "controller_restore": {
        "scope": (),
        "when": ("controller_restore",),
        "steps": ("controller_restore ( job_dequeued | job_routed )*",),
    },
    # Wave spans pair up: a `wave_done` never precedes its wave's
    # `wave_start`; a faulted wave may restart (another start) before it
    # completes.  Scoped per (job, wave) — wave ids repeat across jobs.
    "wave_span": {
        "scope": ("job", "wave"),
        "when": ("wave_start",),
        "steps": ("( wave_start wave_done? )+",),
    },
    # Run-granular resume happens while the job is still live: no
    # `wave_resume` after the job's terminal event.
    "wave_resume": {
        "scope": ("job",),
        "when": ("wave_resume",),
        "steps": ("wave_resume+ ( job_done | job_failed )?",),
    },
    # The §17 two-level fault contract: a hier host-grouping re-plan is
    # journaled only as part of a mesh re-form — the device deaths and
    # the survivor count precede it (a hang-reap re-form may carry no
    # worker_dead), never free-standing, at most one per re-form.
    "hier_reform": {
        "scope": (),
        "when": ("hier_reform",),
        "steps": ("( worker_dead* mesh_reform hier_reform? )+",),
    },
}

#: Event types legitimately OUTSIDE any trace contract (telemetry,
#: phase spans, one-shot markers with no ordering obligation).  DS1101
#: flags an emission site whose event is in neither a contract alphabet
#: nor this tuple; DS1102 checks these names resolve too.
CONTRACT_EXEMPT = (
    "heartbeat_lapse",
    "probe",
    "reassign",
    "capacity_retry",
    "transient_retry",
    "checkpoint_persist",
    "checkpoint_restore",
    "checkpoint_clear",
    "phase_start",
    "phase_end",
    "fused_fallback",
    "worker_join",
    "task_done",
    "device_handle",
    "device_handle_invalidated",
    "device_validate",
    "device_consume",
    "exchange_step",
    "exchange_resize",
    "clock_sync",
    "flight_dump",
    "slice_retired",
    "variant_prewarm",
    "serve_drain",
    "serve_stop",
    "variant_compiled",
    "skew_report",
    "hbm_watermark",
    "fused_exchange_launch",
    "fused_exchange_step",
    "agent_register",
    "agent_heartbeat",
    "health_verdict",
    "agent_degraded",
    "coded_replica_ship",
    "coded_budget_exceeded",
    "plan_decision",
    "plan_override",
    # §17 planning telemetry: per-exchange sizing snapshots with no
    # ordering obligation (the fault-path twin, hier_reform, IS
    # contract-covered above).
    "hier_exchange_plan",
    "hier_exchange_leg",
    # A per-dispatch latency sample (the dispatch_timeout_s policy's
    # measured input): the accept reply and the result race on separate
    # threads, so this marker carries no ordering obligation.
    "job_dispatched",
)

_TOKEN = re.compile(r"[A-Za-z_][A-Za-z0-9_]*|[()|?*+]|\s+")


class ContractError(ValueError):
    """A malformed contract: unknown token, unbalanced grammar."""


def contract_names(contract: dict) -> frozenset[str]:
    """The contract's alphabet: every event name its steps mention."""
    names = set()
    for step in contract["steps"]:
        for tok in _tokens(step):
            if tok not in "()|?*+":
                names.add(tok)
    return frozenset(names)


def _tokens(step: str) -> list[str]:
    out, pos = [], 0
    while pos < len(step):
        m = _TOKEN.match(step, pos)
        if m is None:
            raise ContractError(
                f"bad character {step[pos]!r} in contract step {step!r}"
            )
        pos = m.end()
        tok = m.group()
        if not tok.isspace():
            out.append(tok)
    return out


def compile_contract(contract: dict) -> re.Pattern:
    """Steps -> one regex over ``name,``-encoded traces."""
    parts = []
    for step in contract["steps"]:
        for tok in _tokens(step):
            if tok == "(":
                parts.append("(?:")
            elif tok in ")|?*+":
                parts.append(tok)
            else:
                # Wrap each name with its separator so a postfix ?/*/+
                # binds to the whole token, not the trailing comma.
                parts.append("(?:" + re.escape(tok) + ",)")
    pattern = "".join(parts)
    try:
        return re.compile(pattern)
    except re.error as e:
        raise ContractError(
            f"contract does not compile ({e}): {pattern!r}"
        )


def _as_records(journal) -> list[dict]:
    """Accept an `EventLog`, a list of event objects, or record dicts."""
    events = getattr(journal, "events", None)
    if callable(events):
        journal = events()
    out = []
    for r in journal:
        if isinstance(r, dict):
            out.append(r)
        else:
            out.append(r.to_dict())
    return out


def conformance_report(journal, contracts: dict | None = None) -> dict:
    """Replay a journal against every declared contract.

    Returns ``{"ok": bool, "checked": n_traces, "violations": [...],
    "contracts": {name: {"checked": n, "violations": n}}}``.  A violation
    row names the contract, the scope key of the offending trace, and the
    trace itself — the journal's own evidence.
    """
    contracts = TRACE_CONTRACTS if contracts is None else contracts
    records = _as_records(journal)
    checked_total = 0
    violations = []
    per_contract = {}
    for name, contract in contracts.items():
        alphabet = contract_names(contract)
        pattern = compile_contract(contract)
        when = tuple(contract.get("when", ()))
        scope = tuple(contract.get("scope", ()))
        traces: dict[tuple, list[str]] = {}
        for r in records:
            etype = r.get("type")
            if etype not in alphabet:
                continue
            key = (r.get("src", 0),) + tuple(r.get(f) for f in scope)
            traces.setdefault(key, []).append(etype)
        checked = 0
        bad = 0
        for key, trace in sorted(traces.items(), key=lambda kv: str(kv[0])):
            if when and not any(t in when for t in trace):
                continue
            checked += 1
            if pattern.fullmatch(",".join(trace) + ",") is None:
                bad += 1
                violations.append({
                    "contract": name,
                    "scope": dict(
                        zip(("src",) + scope, key)
                    ),
                    "trace": list(trace),
                })
        per_contract[name] = {"checked": checked, "violations": bad}
        checked_total += checked
    return {
        "ok": not violations,
        "checked": checked_total,
        "violations": violations,
        "contracts": per_contract,
    }


def assert_conformant(journal, contracts: dict | None = None) -> dict:
    """Test helper: raise `AssertionError` naming every violated
    contract; returns the report so callers can add count asserts."""
    report = conformance_report(journal, contracts)
    if not report["ok"]:
        lines = [
            f"{len(report['violations'])} trace-contract violation(s):"
        ]
        for v in report["violations"]:
            lines.append(
                f"  {v['contract']} @ {v['scope']}: {' -> '.join(v['trace'])}"
            )
        raise AssertionError("\n".join(lines))
    return report


def format_conformance(report: dict) -> str:
    """The human table behind ``dsort report --conform``."""
    lines = [
        f"trace conformance: {report['checked']} scoped trace(s) against "
        f"{len(report['contracts'])} contract(s) — "
        + ("OK" if report["ok"] else
           f"{len(report['violations'])} VIOLATION(S)")
    ]
    for name, row in sorted(report["contracts"].items()):
        lines.append(
            f"  {name:<20} {row['checked']:>5} checked  "
            f"{row['violations']:>3} violation(s)"
        )
    for v in report["violations"]:
        lines.append(
            f"  VIOLATED {v['contract']} @ {v['scope']}: "
            + " -> ".join(v["trace"])
        )
    return "\n".join(lines) + "\n"
