"""Declarative protocol state machines: the fleet's lifecycles as data.

`PROTOCOL_SPEC` writes down what the handler code means: the agent-side
link machine (`fleet/agent.py` `_handle`), the controller-side job
machine (`fleet/controller.py` — reader-loop frames plus the dispatch
lane's reply frames), and the serve admission machine
(`serve/admission.py`) — states × `FRAME_TYPES`/`ADMISSION_REASONS`
events × guards → transitions, with per-transition obligations naming
the discharge call that makes the guard true ("persist before
result_ack").

The spec is a PURE dict literal on purpose: the DS10xx checker family
(`analysis/checkers/spec.py`) reads it by PARSING this source — the same
registry discipline as `EVENT_TYPES`/`FRAME_TYPES` — and cross-checks it
against the handler source both ways (every declared handled frame has a
dispatch arm, every arm is declared, no receivable frame is silently
droppable, every obligation's discharge call is present and ordered
before the frame it must precede).  The model checker (`spec/model.py`)
consumes the same structure as its transition oracle.

Spec schema per machine:

- ``registry``: which registry the event alphabet draws from
  (``FRAME_TYPES`` or ``ADMISSION_REASONS``).
- ``handler``: ``(repo-relative path, function name)`` of the dispatch
  site, or absent when coverage is registry-exhaustiveness only.
- ``receives``: the registry subset this side can be sent.
- ``handled``: frames with a dispatch arm in ``handler``.
- ``replies``: frames consumed as request replies (``expect=`` tuples),
  not by the dispatch chain.
- ``internal``: non-frame events (scheduler actions, timeouts).
- ``ignorable``: ``{state: (frames legitimately dropped there,)}``.
- ``states`` / ``initial`` / ``transitions``: the machine proper;
  transitions are ``(state, event, target, guard)`` rows.
- ``obligations``: ``{"file", "function", "must_call", "before_send"?}``
  rows — the named function must call ``must_call``, and when
  ``before_send`` names a frame type, the call must precede every send
  of that frame within the function.
"""

from __future__ import annotations

#: The protocol spec registry (pure literal: parsed, never imported, by
#: the DS10xx checker; imported only by the model checker and tests).
PROTOCOL_SPEC = {
    "agent_link": {
        "doc": "FleetAgent's per-connection frame machine",
        "registry": "FRAME_TYPES",
        "handler": ("dsort_tpu/fleet/agent.py", "_handle"),
        "receives": ("hello", "ping", "submit", "result_ack", "drain",
                     "bye"),
        "handled": ("hello", "ping", "submit", "result_ack", "drain",
                    "bye"),
        "replies": (),
        "internal": ("job_finished",),
        "states": ("attached", "draining", "detached"),
        "initial": "attached",
        "ignorable": {},
        "transitions": (
            ("attached", "hello", "attached",
             "re-handshake: advertise info, report known_jobs statuses, "
             "resend done results whose ack never landed"),
            ("attached", "ping", "attached",
             "heartbeat reply + one bounded telemetry delta"),
            ("attached", "submit", "attached",
             "duplicate-check AND jid reservation atomically under _lock; "
             "duplicate -> idempotent accept + resend held result"),
            ("attached", "result_ack", "attached",
             "drop the held result from the bounded done store"),
            ("attached", "drain", "draining",
             "stop admitting; running jobs finish and their results ship"),
            ("attached", "bye", "detached",
             "controller detached cleanly; agent keeps running"),
            ("attached", "job_finished", "attached",
             "waiter thread records the done entry and pushes the result"),
            ("draining", "hello", "draining",
             "re-handshake still answered while draining"),
            ("draining", "ping", "draining", "heartbeat advertises draining"),
            ("draining", "submit", "draining",
             "rejected with the typed shutting_down reason"),
            ("draining", "result_ack", "draining",
             "late acks for pre-drain jobs still clear the done store"),
            ("draining", "drain", "draining", "idempotent"),
            ("draining", "bye", "detached", "clean detach while draining"),
            ("draining", "job_finished", "draining",
             "in-flight work finishes during the drain"),
        ),
        "obligations": (
            {"file": "dsort_tpu/fleet/agent.py", "function": "_on_submit",
             "must_call": "_push_result",
             "why": "a duplicate dispatch re-sends the held result NOW — "
                    "the controller behind it missed the hello-time resend"},
            {"file": "dsort_tpu/fleet/agent.py", "function": "_waiter",
             "must_call": "_record_done",
             "why": "the result enters the bounded done store before it is "
                    "pushed, so a crashed push can always resend"},
            {"file": "dsort_tpu/fleet/agent.py", "function": "_handle",
             "must_call": "_push_result",
             "why": "the hello arm resends done results for known_jobs — "
                    "the re-attach half of the restart contract"},
        ),
    },
    "controller_job": {
        "doc": "FleetController's per-job lifecycle (queued -> dispatching "
               "-> inflight -> done/failed with at-least-once requeues)",
        "registry": "FRAME_TYPES",
        "handler": ("dsort_tpu/fleet/controller.py", "_reader_loop"),
        "receives": ("welcome", "heartbeat", "accepted", "rejected",
                     "result", "telemetry"),
        "handled": ("result", "telemetry"),
        "replies": ("welcome", "heartbeat", "accepted", "rejected"),
        "internal": ("dispatch", "agent_lost", "restore"),
        "states": ("queued", "dispatching", "inflight", "done", "failed"),
        "initial": "queued",
        # Events with no effect in a state, each a deliberate decision
        # (DS1004 turns an UNdeclared drop into a finding): stale DRR
        # tokens discard at the pop site, late accept/reject replies are
        # discarded by the expect= tuples of a newer round, a dead agent
        # is a no-op for a job it no longer holds, and terminal jobs are
        # popped from the table before the snapshot a restore would read.
        "ignorable": {
            "queued": ("accepted", "rejected"),
            "dispatching": ("dispatch", "restore"),
            "inflight": ("dispatch", "accepted", "rejected"),
            "done": ("dispatch", "accepted", "rejected", "agent_lost",
                     "restore"),
            "failed": ("dispatch", "accepted", "rejected", "agent_lost",
                       "restore"),
        },
        "transitions": (
            ("queued", "dispatch", "dispatching",
             "DRR pop in weighted order; persisted (as inflight) before "
             "the submit frame leaves the controller"),
            ("dispatching", "accepted", "inflight",
             "agent reserved the jid; slot counted against its bound"),
            ("dispatching", "rejected", "queued",
             "agent refused (draining/bad payload) and readmits below the "
             "3x-links exhaustion bound: requeue for another agent"),
            ("dispatching", "rejected", "failed",
             "rejected by every agent (readmits at the exhaustion bound): "
             "typed terminal failure, never an infinite requeue loop"),
            ("dispatching", "agent_lost", "queued",
             "link died mid-dispatch: at-least-once requeue "
             "(job_rerouted, readmits bump)"),
            ("inflight", "result", "done",
             "ok result: completion persisted durably BEFORE result_ack"),
            ("inflight", "result", "failed",
             "error result: typed failure persisted before the ack"),
            ("queued", "result", "done",
             "result from a pre-reroute attempt lands after the timeout "
             "requeue: finish now; the re-queued DRR token goes stale and "
             "the pop site discards it"),
            ("queued", "result", "failed",
             "error result for a requeued job: same race, failure path"),
            ("dispatching", "result", "done",
             "the result outraces the accepted reply (results ride the "
             "reader thread, accepts ride the dispatch lane): finish"),
            ("dispatching", "result", "failed",
             "error result outracing the accept: typed terminal failure"),
            ("inflight", "agent_lost", "queued",
             "agent died holding the job: requeue on a survivor"),
            ("queued", "agent_lost", "queued",
             "death of an agent the job never reached is a no-op"),
            ("done", "result", "done",
             "late duplicate (at-least-once reroute finished elsewhere): "
             "free the slot, re-ack, NEVER re-finish"),
            ("failed", "result", "failed",
             "late duplicate after a failure: same idempotent re-ack"),
            ("queued", "restore", "queued",
             "controller restart: queued jobs reload inside the persisted "
             "policy snapshot in DRR order"),
            ("inflight", "restore", "inflight",
             "restart reconcile: the agent reports the job still running"),
            ("inflight", "restore", "done",
             "restart reconcile: the agent held a finished result for us"),
            ("inflight", "restore", "queued",
             "restart reconcile: the agent no longer knows the job "
             "(or is gone) — requeue, at-least-once"),
        ),
        "obligations": (
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_on_result", "must_call": "_finish_ok",
             "before_send": "result_ack",
             "why": "the completion (which persists durably) happens "
                    "before the ack that lets the agent drop its copy"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_on_result", "must_call": "_finish_error",
             "before_send": "result_ack",
             "why": "the failure path persists before the ack too"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_finish_ok", "must_call": "_flush_persist",
             "why": "durable-state-reflects-completion: fsync+rename "
                    "before the caller acks"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_finish_error", "must_call": "_flush_persist",
             "why": "failed is a terminal state and must survive restart"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_finish_ok", "must_call": "_persist_locked",
             "why": "the snapshot is built under _cv (flush runs outside)"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_agent_down", "must_call": "_requeue_locked",
             "why": "a dead agent's in-flight jobs re-enter the queue — "
                    "the no-lost-job half of at-least-once"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_dispatch_one", "must_call": "_persist_locked",
             "why": "the dispatching->inflight edge is persisted before "
                    "the lane returns"},
            {"file": "dsort_tpu/fleet/controller.py",
             "function": "_requeue_locked", "must_call": "requeue",
             "why": "the DRR token goes back with the job — queue "
                    "conservation (a requeued job the policy never sees "
                    "would strand at depth-accounting time)"},
        ),
    },
    "serve_admission": {
        "doc": "AdmissionController's typed verdict lattice",
        "registry": "ADMISSION_REASONS",
        "receives": ("admitted", "no_capacity", "queue_full",
                     "tenant_limit", "shutting_down", "slo_shed"),
        "handled": (),
        "replies": (),
        "internal": (),
        "covers_registry": True,
        "states": ("submitted", "queued", "rejected"),
        "initial": "submitted",
        "ignorable": {},
        "transitions": (
            ("submitted", "admitted", "queued",
             "counted into the queue depth by the same verdict"),
            ("submitted", "no_capacity", "rejected",
             "every agent draining/absent: the fleet's typed no"),
            ("submitted", "queue_full", "rejected",
             "global bounded-depth backpressure"),
            ("submitted", "tenant_limit", "rejected",
             "per-tenant inflight bound"),
            ("submitted", "shutting_down", "rejected",
             "drain in progress: no new work"),
            ("submitted", "slo_shed", "rejected",
             "live p95 queue wait over the --slo-shed-ms target"),
        ),
        "obligations": (),
    },
}

#: Safety invariant catalog (ARCHITECTURE §16, verbatim): what the model
#: checker proves over every explored interleaving.  Keys are the
#: invariant ids violations carry; values are the one-line contracts.
SPEC_INVARIANTS = {
    "no_lost_job": "every submitted job is always present in the "
                   "controller's table (in memory and, across a crash, "
                   "in the durable snapshot) until a terminal state",
    "no_double_finish": "a job reaches a terminal state at most once — "
                        "late duplicate results never re-finish",
    "durable_before_ack": "whenever a result_ack is on the wire, the "
                          "durable snapshot already records that job's "
                          "terminal state",
    "no_double_run": "an agent starts a given job id at most once "
                     "(at-least-once across agents, at-most-once per "
                     "agent)",
    "bounded_outstanding": "a controller never holds more than its "
                           "outstanding-cap jobs on one agent",
    "queue_conservation": "every queued job holds exactly one DRR "
                          "token, and a token for a non-queued job is "
                          "legal only when that job is terminal (the "
                          "stale token the dispatcher's pop site "
                          "lazily discards)",
}
