"""Spec plane (ARCHITECTURE §16): declarative protocol state machines,
journal trace contracts, and an explicit-state model checker.

Three cooperating pieces, all stdlib-only at import time (the analysis
package's layer contract — DS601 — forbids jax/numpy here):

- `machines` — the controller-side and agent-side job lifecycles and the
  serve admission lifecycle as PURE-LITERAL typed state machines
  (`PROTOCOL_SPEC`), cross-checked against the handler source by the
  DS10xx checker family (`analysis/checkers/spec.py`).
- `contracts` — the `TRACE_CONTRACTS` grammar registry: the per-recovery-
  path event sequences the drill tests used to assert by hand, replayable
  against any journal (`dsort report --conform`, the analyzer's
  `conformance` verdict key, `assert_conformant` in tests) and linted by
  the DS11xx family.
- `model` — the bounded explicit-state model checker behind
  `dsort spec check` / `make spec-smoke`: exhaustive interleavings of
  frame delivery, duplication, agent death, and controller crash against
  the safety invariant catalog, with minimized deterministically
  replayable violation fixtures.
"""

from dsort_tpu.analysis.spec.contracts import (  # noqa: F401
    CONTRACT_EXEMPT,
    TRACE_CONTRACTS,
    assert_conformant,
    conformance_report,
    format_conformance,
)
from dsort_tpu.analysis.spec.machines import PROTOCOL_SPEC  # noqa: F401
