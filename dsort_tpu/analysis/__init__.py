"""Project-native static analysis (`dsort lint`).

The fault-tolerance story rests on invariants that only ever held by
convention: event/counter names must exist in the ``utils.events``
registries (on BOTH sides of the Python/C++ boundary), lock-guarded state
must stay under its lock, traced functions must be side-effect free,
recovery paths must not swallow errors invisibly, and version-drifting JAX
APIs must route through ``utils.compat``.  Recovery code is the least
executed code in the tree — exactly where a convention quietly rots.  This
package machine-checks those invariants on every PR, without running a
cluster or touching a backend.

Entry points: ``dsort lint`` (CLI), `lint_paths` (API), `all_checkers`
(rule catalog).  See ARCHITECTURE.md "Static analysis" for the diagnostic
code catalog and suppression syntax (``# dsort: ignore[DSxxx]``).
"""

from dsort_tpu.analysis.core import (  # noqa: F401
    Diagnostic,
    LintConfig,
    load_baseline,
    load_config,
    write_baseline,
)
from dsort_tpu.analysis.engine import (  # noqa: F401
    Checker,
    LintStats,
    format_json,
    format_sarif,
    format_text,
    lint_paths,
)
from dsort_tpu.analysis.checkers import all_checkers, checker_catalog  # noqa: F401
