"""The lint engine: file discovery, checker dispatch, output formats.

`lint_paths` walks the requested files/directories, parses each Python file
once, hands every file to the checkers whose scope matches, and filters the
findings through per-line suppressions and the baseline.  Checkers are
plain classes registered in `dsort_tpu.analysis.checkers`; the engine knows
nothing about individual rules.

The project registries (event types / counters in ``utils/events.py``, the
native event map in ``runtime/native.py``) are read by PARSING their source,
not importing it: the linter must see exactly what is written in the tree it
checks (an out-of-date installed copy must not mask drift), and checking a
tree must never initialize a JAX backend.
"""

from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import os

from dsort_tpu.analysis.core import (
    Diagnostic,
    LintConfig,
    is_suppressed,
    load_baseline,
    suppressions,
)


class FileContext:
    """Everything checkers may need about one file, parsed once."""

    def __init__(self, path: str, relpath: str, source: str, config: LintConfig):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.config = config
        self.is_python = relpath.endswith(".py")
        self.tree: ast.AST | None = None
        if self.is_python:
            self.tree = ast.parse(source, filename=path)


class Checker:
    """Base class: subclasses set `name`, `codes`, `scope`, and `check`.

    ``scope`` is a tuple of fnmatch globs over repo-relative paths; the
    engine only hands a checker files it matches.  ``codes`` documents every
    diagnostic the checker can produce (the catalog rendered in
    ARCHITECTURE.md and enforced by tests).

    A checker with ``project = True`` works over the whole lint run rather
    than file by file: the engine calls `check_project` ONCE, after the
    per-file phase, with a `ProjectContext` (the DS6xx import-graph pass —
    a layer contract is a property of the tree, not of any one file).
    Project findings skip the per-file result cache (their inputs span
    files) but pass through suppressions and the baseline like any other.
    """

    name: str = ""
    codes: dict[str, str] = {}
    scope: tuple[str, ...] = ("*.py",)
    project: bool = False

    def __init__(self, scope: tuple[str, ...] | None = None):
        # Tests point a checker at fixture trees outside its default scope.
        if scope is not None:
            self.scope = tuple(scope)

    def matches(self, relpath: str) -> bool:
        rel = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rel, pat) for pat in self.scope)

    def check(self, ctx: FileContext) -> list[Diagnostic]:  # pragma: no cover
        raise NotImplementedError

    def check_project(
        self, project: "ProjectContext"
    ) -> list[Diagnostic]:  # pragma: no cover
        raise NotImplementedError


class ProjectContext:
    """What a project-wide checker sees: the run's config, the set of
    repo-relative Python files actually linted, and an on-demand source
    loader (a cross-file pass may need to read files OUTSIDE the linted
    set — e.g. the import closure of a declared-pure module when only one
    changed file was passed)."""

    def __init__(self, config: LintConfig, relpaths: set[str]):
        self.config = config
        self.relpaths = relpaths  # '/'-normalized, root-relative
        self._sources: dict[str, str | None] = {}

    def source(self, relpath: str) -> str | None:
        rel = relpath.replace(os.sep, "/")
        if rel not in self._sources:
            path = self.config.abspath(rel)
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    self._sources[rel] = f.read()
            except OSError:
                self._sources[rel] = None
        return self._sources[rel]


# -- project registries, read statically ------------------------------------


def _dict_literal_keys(tree: ast.AST, names: set[str]) -> dict[str, list[str]]:
    """String keys of top-level dict literals assigned to ``names``.

    Matches both plain and annotated assignments (``X: dict[str, str] = {}``).
    """
    out: dict[str, list[str]] = {}
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id in names
                and isinstance(value, ast.Dict)
            ):
                out[t.id] = [
                    k.value
                    for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                ]
    return out


def _tuple_literal_strs(tree: ast.AST, names: set[str]) -> dict[str, list[str]]:
    """String elements of top-level tuple/list literals assigned to
    ``names`` (the ``ADMISSION_REASONS`` vocabulary shape)."""
    out: dict[str, list[str]] = {}
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id in names
                and isinstance(value, (ast.Tuple, ast.List))
            ):
                out[t.id] = [
                    e.value
                    for e in value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
    return out


class Registries:
    """Lazily parsed project vocabularies shared by the registry checkers."""

    def __init__(self, config: LintConfig):
        self._config = config
        self._loaded = False
        self.event_types: set[str] = set()
        self.counters: set[str] = set()
        self.native_map: set[str] = set()  # native line names the parser maps
        self.frame_types: set[str] = set()  # fleet wire-protocol vocabulary
        self.admission_reasons: set[str] = set()  # typed verdict vocabulary
        self.missing: list[str] = []  # registry files that could not be read
        self.proto_missing: list[str] = []  # protocol registry files missing

    def _parse(self, relpath: str, sink: list[str]) -> ast.AST | None:
        path = self._config.abspath(relpath)
        if path and os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                return ast.parse(f.read(), filename=path)
        sink.append(relpath)
        return None

    def load(self) -> "Registries":
        if self._loaded:
            return self
        self._loaded = True
        tree = self._parse(self._config.registry_path, self.missing)
        if tree is not None:
            found = _dict_literal_keys(tree, {"EVENT_TYPES", "COUNTERS"})
            self.event_types = set(found.get("EVENT_TYPES", []))
            self.counters = set(found.get("COUNTERS", []))
        tree = self._parse(self._config.native_map_path, self.missing)
        if tree is not None:
            found = _dict_literal_keys(tree, {"_COORD_EVENT_TYPES"})
            self.native_map = set(found.get("_COORD_EVENT_TYPES", []))
        tree = self._parse(self._config.proto_registry_path, self.proto_missing)
        if tree is not None:
            found = _dict_literal_keys(tree, {"FRAME_TYPES"})
            self.frame_types = set(found.get("FRAME_TYPES", []))
        tree = self._parse(
            self._config.admission_registry_path, self.proto_missing
        )
        if tree is not None:
            found = _tuple_literal_strs(tree, {"ADMISSION_REASONS"})
            self.admission_reasons = set(found.get("ADMISSION_REASONS", []))
        return self


def _spmd_required_files(config: LintConfig) -> list[str]:
    """Repo-relative files the SPMD verifier registry requires contracts
    from (cache-key inputs; empty when the registry is absent/unreadable)."""
    path = config.abspath(config.spmd_registry_path)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return []
    found = _tuple_literal_strs(tree, {"SPMD_REQUIRED"})
    return found.get("SPMD_REQUIRED", [])


# -- per-file result cache ---------------------------------------------------

#: Bump when the cached-diagnostic shape or engine semantics change.
CACHE_SCHEMA = 1


class ResultCache:
    """Content-hash keyed per-file diagnostic cache (``make lint`` stays
    interactive on the grown tree).

    One entry per file: sha256 of the source -> the file's post-suppression,
    PRE-baseline diagnostics (suppressions are a function of the content —
    safe to bake in; the baseline can change independently — applied at
    read time).  The whole cache is keyed by a config fingerprint covering
    the checker set (names, codes, scopes), the enabled set, and the
    CONTENT of every registry source the per-file checkers read — editing
    ``events.py`` must invalidate every cached registry finding.  Project-
    wide (cross-file) checkers never cache: their inputs span files.
    """

    def __init__(self, path: str, config: LintConfig, checkers: list):
        self.path = path
        self._root = config.root
        self._key = self._config_key(config, checkers)
        self._files: dict[str, dict] = {}
        self._dirty = False
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if (
                data.get("schema") == CACHE_SCHEMA
                and data.get("config_key") == self._key
            ):
                self._files = dict(data.get("files", {}))
        except (OSError, json.JSONDecodeError, ValueError):
            pass  # a torn/stale cache regenerates; never fatal

    @staticmethod
    def _config_key(config: LintConfig, checkers: list) -> str:
        h = hashlib.sha256()
        h.update(f"schema={CACHE_SCHEMA}".encode())
        for c in sorted(checkers, key=lambda c: c.name):
            h.update(
                f"{c.name}|{sorted(c.codes)}|{sorted(c.scope)}".encode()
            )
        h.update(repr(sorted(config.enable)).encode())
        # The analysis package's OWN sources participate: a checker bugfix
        # that keeps its name/codes/scope must still invalidate every
        # cached verdict, without anyone remembering to bump CACHE_SCHEMA.
        pkg = os.path.dirname(os.path.abspath(__file__))
        for dirpath, dirnames, names in os.walk(pkg):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS
            )
            for name in sorted(names):
                if name.endswith(".py"):
                    try:
                        with open(os.path.join(dirpath, name), "rb") as f:
                            h.update(hashlib.sha256(f.read()).digest())
                    except OSError:
                        h.update(b"<unreadable>")
        for rel in (
            config.registry_path, config.native_map_path,
            config.proto_registry_path, config.admission_registry_path,
            config.spec_registry_path, config.contracts_registry_path,
            config.spmd_registry_path,
        ):
            path = config.abspath(rel)
            h.update(rel.encode())
            try:
                with open(path, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"<missing>")
        # The SPMD verifier proves OTHER modules' closed forms: every file
        # the spmd registry requires a contract from participates in the
        # key, so editing a perm builder or cap ladder in exchange.py
        # invalidates every cached verdict (not just exchange.py's own —
        # ring_kernel.py's layout proof evaluates exchange-derived caps).
        for rel in sorted(_spmd_required_files(config)):
            path = config.abspath(rel)
            h.update(rel.encode())
            try:
                with open(path, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"<missing>")
        return h.hexdigest()

    @staticmethod
    def _content_key(source: str) -> str:
        return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()

    def get(self, relpath: str, source: str) -> list[Diagnostic] | None:
        entry = self._files.get(relpath)
        if entry is None or entry.get("hash") != self._content_key(source):
            return None
        try:
            return [Diagnostic(**d) for d in entry["diags"]]
        except (KeyError, TypeError):
            return None

    def put(self, relpath: str, source: str, diags: list[Diagnostic]) -> None:
        self._files[relpath] = {
            "hash": self._content_key(source),
            "diags": [d.to_dict() for d in diags],
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        # Prune entries whose file is gone: without this the cache grows
        # monotonically across renames/deletes and one-off explicit-path
        # runs.
        root = self._root
        self._files = {
            rel: entry
            for rel, entry in self._files.items()
            if os.path.exists(os.path.join(root, rel.replace("/", os.sep)))
        }
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "schema": CACHE_SCHEMA,
                        "config_key": self._key,
                        "files": self._files,
                    },
                    f,
                )
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass  # the cache is an optimization; a full disk is not fatal


# -- the run ----------------------------------------------------------------

_LINTABLE = (".py", ".cpp", ".cc", ".h", ".hpp")
_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", "node_modules"}


def discover(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
                for name in sorted(names):
                    if name.endswith(_LINTABLE):
                        files.append(os.path.join(dirpath, name))
        elif p.endswith(_LINTABLE):
            files.append(p)
    return files


class LintStats:
    """Per-checker cost/yield accounting for one `lint_paths` run.

    ``checkers`` maps checker name -> ``{"seconds", "findings", "files",
    "project"}`` — wall time inside the checker, pre-baseline finding
    count, files handed to it (0 for a project pass, which runs once), and
    whether it ran as the cross-file phase.  ``files``/``cached`` count the
    run's inputs and cache hits; cache-served files charge no checker time,
    so a warm run's table shows where the cold cost actually lives."""

    def __init__(self):
        self.files = 0
        self.cached = 0
        self.checkers: dict[str, dict] = {}

    def add(self, name: str, seconds: float, findings: int, project: bool):
        row = self.checkers.setdefault(
            name,
            {"seconds": 0.0, "findings": 0, "files": 0, "project": project},
        )
        row["seconds"] += seconds
        row["findings"] += findings
        if not project:
            row["files"] += 1

    def format(self) -> str:
        rows = sorted(
            self.checkers.items(),
            key=lambda kv: -kv[1]["seconds"],
        )
        width = max([len("checker")] + [len(n) for n, _ in rows])
        lines = [
            f"{'checker':<{width}}  {'phase':<7}  {'files':>5}  "
            f"{'findings':>8}  {'seconds':>8}",
        ]
        for name, row in rows:
            phase = "project" if row["project"] else "file"
            files = "-" if row["project"] else str(row["files"])
            lines.append(
                f"{name:<{width}}  {phase:<7}  {files:>5}  "
                f"{row['findings']:>8}  {row['seconds']:>8.3f}"
            )
        total = sum(r["seconds"] for _, r in rows)
        lines.append(
            f"{len(self.checkers)} checker(s), {self.files} file(s) "
            f"({self.cached} cache hit(s)), {total:.3f}s in checkers"
        )
        return "\n".join(lines) + "\n"


def lint_paths(
    paths: list[str],
    config: LintConfig | None = None,
    checkers: list[Checker] | None = None,
    cache_path: str | None = None,
    stats: LintStats | None = None,
) -> list[Diagnostic]:
    """Run ``checkers`` (default: all registered, minus config disables)
    over ``paths``; returns baseline- and suppression-filtered diagnostics
    sorted by (path, line, col, code).  ``cache_path`` enables the
    per-file result cache (the CLI's default; the API default stays
    cache-free so tests and tools are hermetic).  ``stats``, when given,
    is filled with per-checker wall time and finding counts."""
    import time

    from dsort_tpu.analysis.checkers import all_checkers

    config = config or LintConfig()
    if checkers is None:
        checkers = all_checkers()
        if config.enable:
            known = {c.name for c in checkers}
            unknown = sorted(set(config.enable) - known)
            if unknown:
                # A typo'd name would silently disable a checker and let
                # the gate pass vacuously — same doctrine as the CLI's
                # missing-path error.
                raise ValueError(
                    f"[tool.dsort.lint] enable names unknown checkers "
                    f"{unknown}; known: {sorted(known)}"
                )
            checkers = [c for c in checkers if c.name in config.enable]
    file_checkers = [c for c in checkers if not c.project]
    project_checkers = [c for c in checkers if c.project]
    registries = Registries(config)
    baseline = load_baseline(config.abspath(config.baseline))
    cache = (
        ResultCache(cache_path, config, checkers) if cache_path else None
    )
    diags: list[Diagnostic] = []
    relpaths: set[str] = set()
    for path in discover(paths):
        rel = os.path.relpath(path, config.root)
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        rel_slash = rel.replace(os.sep, "/")
        relpaths.add(rel_slash)
        if stats is not None:
            stats.files += 1
        if cache is not None:
            cached = cache.get(rel_slash, source)
            if cached is not None:
                if stats is not None:
                    stats.cached += 1
                diags.extend(
                    d for d in cached if d.baseline_key not in baseline
                )
                continue
        raw: list[Diagnostic] = []
        try:
            ctx = FileContext(path, rel, source, config)
        except SyntaxError as e:
            raw.append(
                Diagnostic(
                    rel_slash, e.lineno or 1, 0, "DS001",
                    f"syntax error: {e.msg}",
                )
            )
        else:
            ctx.registries = registries  # shared lazily-loaded vocabularies
            supp = suppressions(source)
            for checker in file_checkers:
                if not checker.matches(rel):
                    continue
                t0 = time.perf_counter()
                found = checker.check(ctx)
                if stats is not None:
                    stats.add(
                        checker.name, time.perf_counter() - t0,
                        len(found), project=False,
                    )
                raw.extend(d for d in found if not is_suppressed(d, supp))
        if cache is not None:
            cache.put(rel_slash, source, raw)
        diags.extend(d for d in raw if d.baseline_key not in baseline)
    if project_checkers:
        project = ProjectContext(config, relpaths)
        supp_cache: dict[str, dict] = {}
        for checker in project_checkers:
            t0 = time.perf_counter()
            found = checker.check_project(project)
            if stats is not None:
                stats.add(
                    checker.name, time.perf_counter() - t0,
                    len(found), project=True,
                )
            for d in found:
                if d.path not in supp_cache:
                    src = project.source(d.path)
                    supp_cache[d.path] = suppressions(src) if src else {}
                if (
                    not is_suppressed(d, supp_cache[d.path])
                    and d.baseline_key not in baseline
                ):
                    diags.append(d)
    if cache is not None:
        cache.save()
    # Identical findings collapse (Diagnostic is frozen/hashable): run-wide
    # diagnostics like DS105 anchor on a shared path and report once.
    return sorted(set(diags), key=lambda d: (d.path, d.line, d.col, d.code))


def format_text(diags: list[Diagnostic]) -> str:
    lines = [d.format() for d in diags]
    errors = sum(d.severity == "error" for d in diags)
    lines.append(
        f"dsort lint: {errors} error(s), {len(diags) - errors} warning(s)"
    )
    return "\n".join(lines) + "\n"


def format_json(diags: list[Diagnostic]) -> str:
    return json.dumps([d.to_dict() for d in diags], indent=1) + "\n"


def format_sarif(diags: list[Diagnostic]) -> str:
    """SARIF 2.1.0 log: one run, the full checker catalog as driver rules
    (so code-scanning UIs show rule help even for clean runs), one result
    per diagnostic.  Columns convert to SARIF's 1-based convention; paths
    are already '/'-separated repo-relative URIs."""
    from dsort_tpu.analysis.checkers import checker_catalog

    rules = [
        {
            "id": code,
            "name": code,
            "shortDescription": {"text": desc},
            "properties": {"checker": checker},
        }
        for checker, codes in sorted(checker_catalog().items())
        for code, desc in sorted(codes.items())
    ]
    results = [
        {
            "ruleId": d.code,
            "level": "error" if d.severity == "error" else "warning",
            "message": {"text": d.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": d.path},
                        "region": {
                            "startLine": d.line,
                            "startColumn": d.col + 1,
                        },
                    }
                }
            ],
        }
        for d in diags
    ]
    log = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "dsort-lint",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(log, indent=1) + "\n"
