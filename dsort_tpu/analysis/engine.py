"""The lint engine: file discovery, checker dispatch, output formats.

`lint_paths` walks the requested files/directories, parses each Python file
once, hands every file to the checkers whose scope matches, and filters the
findings through per-line suppressions and the baseline.  Checkers are
plain classes registered in `dsort_tpu.analysis.checkers`; the engine knows
nothing about individual rules.

The project registries (event types / counters in ``utils/events.py``, the
native event map in ``runtime/native.py``) are read by PARSING their source,
not importing it: the linter must see exactly what is written in the tree it
checks (an out-of-date installed copy must not mask drift), and checking a
tree must never initialize a JAX backend.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os

from dsort_tpu.analysis.core import (
    Diagnostic,
    LintConfig,
    is_suppressed,
    load_baseline,
    suppressions,
)


class FileContext:
    """Everything checkers may need about one file, parsed once."""

    def __init__(self, path: str, relpath: str, source: str, config: LintConfig):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.config = config
        self.is_python = relpath.endswith(".py")
        self.tree: ast.AST | None = None
        if self.is_python:
            self.tree = ast.parse(source, filename=path)


class Checker:
    """Base class: subclasses set `name`, `codes`, `scope`, and `check`.

    ``scope`` is a tuple of fnmatch globs over repo-relative paths; the
    engine only hands a checker files it matches.  ``codes`` documents every
    diagnostic the checker can produce (the catalog rendered in
    ARCHITECTURE.md and enforced by tests).
    """

    name: str = ""
    codes: dict[str, str] = {}
    scope: tuple[str, ...] = ("*.py",)

    def __init__(self, scope: tuple[str, ...] | None = None):
        # Tests point a checker at fixture trees outside its default scope.
        if scope is not None:
            self.scope = tuple(scope)

    def matches(self, relpath: str) -> bool:
        rel = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rel, pat) for pat in self.scope)

    def check(self, ctx: FileContext) -> list[Diagnostic]:  # pragma: no cover
        raise NotImplementedError


# -- project registries, read statically ------------------------------------


def _dict_literal_keys(tree: ast.AST, names: set[str]) -> dict[str, list[str]]:
    """String keys of top-level dict literals assigned to ``names``.

    Matches both plain and annotated assignments (``X: dict[str, str] = {}``).
    """
    out: dict[str, list[str]] = {}
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id in names
                and isinstance(value, ast.Dict)
            ):
                out[t.id] = [
                    k.value
                    for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                ]
    return out


class Registries:
    """Lazily parsed project vocabularies shared by the registry checkers."""

    def __init__(self, config: LintConfig):
        self._config = config
        self._loaded = False
        self.event_types: set[str] = set()
        self.counters: set[str] = set()
        self.native_map: set[str] = set()  # native line names the parser maps
        self.missing: list[str] = []  # registry files that could not be read

    def load(self) -> "Registries":
        if self._loaded:
            return self
        self._loaded = True
        reg = self._config.abspath(self._config.registry_path)
        if reg and os.path.exists(reg):
            with open(reg, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=reg)
            found = _dict_literal_keys(tree, {"EVENT_TYPES", "COUNTERS"})
            self.event_types = set(found.get("EVENT_TYPES", []))
            self.counters = set(found.get("COUNTERS", []))
        else:
            self.missing.append(self._config.registry_path)
        nat = self._config.abspath(self._config.native_map_path)
        if nat and os.path.exists(nat):
            with open(nat, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=nat)
            found = _dict_literal_keys(tree, {"_COORD_EVENT_TYPES"})
            self.native_map = set(found.get("_COORD_EVENT_TYPES", []))
        else:
            self.missing.append(self._config.native_map_path)
        return self


# -- the run ----------------------------------------------------------------

_LINTABLE = (".py", ".cpp", ".cc", ".h", ".hpp")
_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", "node_modules"}


def discover(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
                for name in sorted(names):
                    if name.endswith(_LINTABLE):
                        files.append(os.path.join(dirpath, name))
        elif p.endswith(_LINTABLE):
            files.append(p)
    return files


def lint_paths(
    paths: list[str],
    config: LintConfig | None = None,
    checkers: list[Checker] | None = None,
) -> list[Diagnostic]:
    """Run ``checkers`` (default: all registered, minus config disables)
    over ``paths``; returns baseline- and suppression-filtered diagnostics
    sorted by (path, line, col, code)."""
    from dsort_tpu.analysis.checkers import all_checkers

    config = config or LintConfig()
    if checkers is None:
        checkers = all_checkers()
        if config.enable:
            known = {c.name for c in checkers}
            unknown = sorted(set(config.enable) - known)
            if unknown:
                # A typo'd name would silently disable a checker and let
                # the gate pass vacuously — same doctrine as the CLI's
                # missing-path error.
                raise ValueError(
                    f"[tool.dsort.lint] enable names unknown checkers "
                    f"{unknown}; known: {sorted(known)}"
                )
            checkers = [c for c in checkers if c.name in config.enable]
    registries = Registries(config)
    baseline = load_baseline(config.abspath(config.baseline))
    diags: list[Diagnostic] = []
    for path in discover(paths):
        rel = os.path.relpath(path, config.root)
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        try:
            ctx = FileContext(path, rel, source, config)
        except SyntaxError as e:
            diags.append(
                Diagnostic(
                    rel.replace(os.sep, "/"), e.lineno or 1, 0, "DS001",
                    f"syntax error: {e.msg}",
                )
            )
            continue
        ctx.registries = registries  # shared lazily-loaded vocabularies
        supp = suppressions(source)
        for checker in checkers:
            if not checker.matches(rel):
                continue
            for d in checker.check(ctx):
                if not is_suppressed(d, supp) and d.baseline_key not in baseline:
                    diags.append(d)
    # Identical findings collapse (Diagnostic is frozen/hashable): run-wide
    # diagnostics like DS105 anchor on a shared path and report once.
    return sorted(set(diags), key=lambda d: (d.path, d.line, d.col, d.code))


def format_text(diags: list[Diagnostic]) -> str:
    lines = [d.format() for d in diags]
    errors = sum(d.severity == "error" for d in diags)
    lines.append(
        f"dsort lint: {errors} error(s), {len(diags) - errors} warning(s)"
    )
    return "\n".join(lines) + "\n"


def format_json(diags: list[Diagnostic]) -> str:
    return json.dumps([d.to_dict() for d in diags], indent=1) + "\n"
