"""Shared AST helpers for the checkers (stdlib-only, like everything in
`dsort_tpu.analysis`).  One copy: a fix to callee resolution or scope
walking must not silently diverge between checker modules."""

from __future__ import annotations

import ast


def callee_basename(func: ast.expr) -> str | None:
    """Rightmost name of a call target: ``jax.jit`` -> ``jit``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def own_nodes(fn):
    """Every node of ``fn``'s body that is not inside a nested def (nested
    functions run on other stacks and are scanned as their own scopes)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
