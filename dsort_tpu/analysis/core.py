"""Lint-engine core: diagnostics, suppressions, baseline, configuration.

The pieces every checker shares.  A `Diagnostic` is one finding — stable
``code`` (``DSxxx``), severity, file/line/column span, message.  Suppression
is per-line (``# dsort: ignore[DS201]`` in Python, ``// dsort: ignore[...]``
in C++ — bare ``ignore`` silences every code on that line).  The baseline
file records findings that are tolerated for now; matching deliberately
ignores line numbers so unrelated edits above a baselined site do not
resurrect it.  The shipped tree keeps the baseline EMPTY — it exists so a
future emergency has an escape hatch that is visible in review, not so
violations can accumulate silently.

Everything in the analysis package is stdlib-only (``ast``, ``tomllib``,
``json``): linting a tree never touches a JAX backend or device.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

#: Severity levels, in increasing order of badness.  ``error`` fails the
#: lint run; ``warning`` is reported but does not affect the exit code.
SEVERITIES = ("warning", "error")


@dataclasses.dataclass(frozen=True, order=True)
class Diagnostic:
    """One finding: a stable code anchored to a source span."""

    path: str  # repo-relative, '/'-separated (stable across platforms)
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    code: str  # "DS101" etc. — see the checker catalog in ARCHITECTURE.md
    message: str
    severity: str = "error"

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
        }

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col + 1}: "
            f"{self.code} [{self.severity}] {self.message}"
        )

    @property
    def baseline_key(self) -> tuple:
        """Line-independent identity: edits above a site must not churn the
        baseline, so only (path, code, message) participate."""
        return (self.path, self.code, self.message)


# -- suppression comments ---------------------------------------------------

#: ``# dsort: ignore`` or ``# dsort: ignore[DS101,DS202]`` (Python), same
#: after ``//`` in C++.  Matching is per physical line.
_SUPPRESS_RE = re.compile(
    r"(?:#|//)\s*dsort:\s*ignore(?:\[(?P<codes>[A-Z0-9, ]+)\])?"
)


def suppressions(source: str) -> dict[int, set[str] | None]:
    """Map of 1-based line -> suppressed codes (None = all codes)."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = m.group("codes")
        out[i] = (
            None
            if codes is None
            else {c.strip() for c in codes.split(",") if c.strip()}
        )
    return out


def is_suppressed(diag: Diagnostic, supp: dict[int, set[str] | None]) -> bool:
    codes = supp.get(diag.line, ...)
    if codes is ...:
        return False
    return codes is None or diag.code in codes


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str | None) -> set[tuple]:
    """Baseline keys from a JSON file (missing file = empty baseline)."""
    if not path or not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {
        (e["path"], e["code"], e["message"])
        for e in data.get("entries", [])
    }

def write_baseline(path: str, diags: list[Diagnostic]) -> None:
    entries = [
        {"path": d.path, "code": d.code, "message": d.message}
        for d in sorted(diags)
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1)
        f.write("\n")


# -- configuration ----------------------------------------------------------


@dataclasses.dataclass
class LintConfig:
    """Engine configuration (the ``[tool.dsort.lint]`` pyproject table).

    ``root`` anchors every relative path: scope globs match against
    root-relative file paths, and ``registry_path``/``native_map_path``/
    ``proto_registry_path``/``admission_registry_path`` default to the
    project's own registry sources so the registry checkers read THE
    vocabulary, not a copy.  ``layers`` is the ``[tool.dsort.lint.layers]``
    sub-table: module pattern (``pkg.mod`` or ``pkg.sub.*``) -> tuple of
    import roots that module must never reach, transitively, at import
    time (the DS6xx purity contract).
    """

    root: str = "."
    enable: tuple[str, ...] = ()  # empty = every registered checker
    baseline: str | None = None
    registry_path: str = os.path.join("dsort_tpu", "utils", "events.py")
    native_map_path: str = os.path.join("dsort_tpu", "runtime", "native.py")
    proto_registry_path: str = os.path.join("dsort_tpu", "fleet", "proto.py")
    admission_registry_path: str = os.path.join(
        "dsort_tpu", "serve", "admission.py"
    )
    spec_registry_path: str = os.path.join(
        "dsort_tpu", "analysis", "spec", "machines.py"
    )
    contracts_registry_path: str = os.path.join(
        "dsort_tpu", "analysis", "spec", "contracts.py"
    )
    spmd_registry_path: str = os.path.join(
        "dsort_tpu", "analysis", "spmd", "registry.py"
    )
    layers: dict = dataclasses.field(default_factory=dict)

    def abspath(self, rel: str | None) -> str | None:
        if rel is None:
            return None
        return rel if os.path.isabs(rel) else os.path.join(self.root, rel)


def _read_lint_table(path: str) -> dict:
    """The ``[tool.dsort.lint]`` table of a pyproject.toml (including the
    ``[tool.dsort.lint.layers]`` sub-table, surfaced as ``table["layers"]``).

    Uses ``tomllib`` when available (3.11+); on 3.10 falls back to a
    section-scoped reader that handles exactly the value shapes these
    tables use (strings, string arrays, and quoted-dotted-name keys) — no
    dependency may be added for this.
    """
    try:
        import tomllib
    except ImportError:
        tomllib = None
    if tomllib is not None:
        with open(path, "rb") as f:
            return (
                tomllib.load(f).get("tool", {}).get("dsort", {}).get("lint", {})
            )
    table: dict = {}
    section = None  # "lint" | "layers" | None
    with open(path, encoding="utf-8") as f:
        lines = iter(f)
        for raw in lines:
            line = raw.strip()
            if line.startswith("["):
                section = {
                    "[tool.dsort.lint]": "lint",
                    "[tool.dsort.lint.layers]": "layers",
                }.get(line)
                continue
            if section is None or "=" not in line or line.startswith("#"):
                continue
            key, _, val = line.partition("=")
            key, val = key.strip().strip('"'), val.strip()
            # Multi-line arrays: accumulate until the closing bracket.
            while val.startswith("[") and "]" not in val:
                val += " " + next(lines, "]").strip()
            if val.startswith("["):
                parsed = re.findall(r'"([^"]*)"', val)
            elif val.startswith('"'):
                parsed = val.strip('"')
            else:
                continue
            if section == "layers":
                table.setdefault("layers", {})[key] = parsed
            else:
                table[key] = parsed
    return table


def load_config(root: str) -> LintConfig:
    """Read ``[tool.dsort.lint]`` from ``<root>/pyproject.toml`` (absent
    file or table = defaults)."""
    cfg = LintConfig(root=root)
    py = os.path.join(root, "pyproject.toml")
    if not os.path.exists(py):
        return cfg
    table = _read_lint_table(py)
    if "enable" in table:
        cfg.enable = tuple(table["enable"])
    if "baseline" in table:
        cfg.baseline = table["baseline"]
    if "registry" in table:
        cfg.registry_path = table["registry"]
    if "native_map" in table:
        cfg.native_map_path = table["native_map"]
    if "proto_registry" in table:
        cfg.proto_registry_path = table["proto_registry"]
    if "admission_registry" in table:
        cfg.admission_registry_path = table["admission_registry"]
    if "spec_registry" in table:
        cfg.spec_registry_path = table["spec_registry"]
    if "contracts_registry" in table:
        cfg.contracts_registry_path = table["contracts_registry"]
    if "spmd_registry" in table:
        cfg.spmd_registry_path = table["spmd_registry"]
    if "layers" in table:
        cfg.layers = {
            str(mod): tuple(forbidden)
            for mod, forbidden in dict(table["layers"]).items()
        }
    return cfg
