"""Protocol/state-machine coverage checker for the fleet wire plane.

`fleet.proto.FRAME_TYPES` is the wire vocabulary (send_frame refuses
unregistered types at RUNTIME — but only on the path that runs, the DS101
argument all over again), and `serve.admission.ADMISSION_REASONS` is the
typed verdict vocabulary.  This checker moves both guarantees to lint
time, mirroring the DS101-105 registry-coverage design: the registries
are read by PARSING their sources (`fleet/proto.py`,
`serve/admission.py` — configurable as ``proto_registry`` /
``admission_registry`` in ``[tool.dsort.lint]``), never imported.

Codes
  DS801  a frame literal — a ``{"type": "x", ...}`` header dict or a
         ``header["type"] == "x"`` / ``.get("type") == "x"`` comparison —
         names a type absent from ``FRAME_TYPES``: the send would raise
         at runtime, the comparison is a dead branch hiding a typo
  DS802  a receive dispatch (an ``==``-chain of two or more arms over a
         frame's ``type``; a lone equality test is a reply guard, not a
         dispatch) covers only part of the registered vocabulary and has
         NO default branch: a frame type added to the registry would be
         silently dropped here (every dispatch must handle or explicitly
         default)
  DS803  an admission-reason literal — ``reason=`` in an `Admission`
         construction, or a comparison against ``.reason`` /
         ``.get("reason")`` — is absent from ``ADMISSION_REASONS``
  DS804  a protocol registry source could not be read (configuration
         error; mirrors DS105)

The frame rules (DS801/DS802) engage only in files that import
``fleet.proto`` — a ``{"type": ...}`` dict in unrelated code (a Chrome
trace event, a JSON schema) is not a frame.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext


def _type_key_expr(expr: ast.expr, key: str) -> bool:
    """True for ``X[key]`` or ``X.get(key, ...)``."""
    if (
        isinstance(expr, ast.Subscript)
        and isinstance(expr.slice, ast.Constant)
        and expr.slice.value == key
    ):
        return True
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "get"
        and bool(expr.args)
        and isinstance(expr.args[0], ast.Constant)
        and expr.args[0].value == key
    )


def _eq_literal(
    test: ast.expr, key: str, aliases: set[str] = frozenset()
) -> str | None:
    """The string literal of a ``X[key] == "lit"`` comparison (or
    ``alias == "lit"`` for a name bound from such an expression — the
    ``ftype = header["type"]`` idiom), else None."""
    if not (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Eq)
        and isinstance(test.comparators[0], ast.Constant)
        and isinstance(test.comparators[0].value, str)
    ):
        return None
    left = test.left
    if not (
        _type_key_expr(left, key)
        or (isinstance(left, ast.Name) and left.id in aliases)
    ):
        return None
    return test.comparators[0].value


def _key_aliases(tree: ast.AST, key: str) -> set[str]:
    """Names assigned from ``X[key]`` / ``X.get(key)`` anywhere in the
    module (the local rebind every dispatch loop uses)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and _type_key_expr(node.value, key)
        ):
            out.add(node.targets[0].id)
    return out


class ProtocolChecker(Checker):
    name = "protocol"
    codes = {
        "DS801": "frame type not registered in fleet.proto.FRAME_TYPES",
        "DS802": "receive dispatch misses registered frame types with no "
                 "default branch",
        "DS803": "admission reason not registered in "
                 "serve.admission.ADMISSION_REASONS",
        "DS804": "protocol registry source unreadable",
    }
    scope = ("dsort_tpu/fleet/*.py", "dsort_tpu/serve/*.py")

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        regs = ctx.registries.load()
        diags = [
            Diagnostic(miss.replace("\\", "/"), 1, 0, "DS804",
                       "cannot read protocol registry source (check "
                       "[tool.dsort.lint] proto_registry/admission_registry "
                       "paths)")
            for miss in regs.proto_missing
        ]
        if self._imports_proto(ctx):
            diags.extend(self._check_frames(ctx, regs))
        diags.extend(self._check_reasons(ctx, regs))
        return diags

    @staticmethod
    def _imports_proto(ctx: FileContext) -> bool:
        # The registry definition module itself only *defines* the types.
        if ctx.relpath == ctx.config.proto_registry_path.replace("\\", "/"):
            return False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module and "fleet.proto" in node.module:
                    return True
            elif isinstance(node, ast.Import):
                if any("fleet.proto" in a.name for a in node.names):
                    return True
        return False

    # -- DS801 / DS802 -------------------------------------------------------

    def _check_frames(self, ctx, regs) -> list[Diagnostic]:
        if not regs.frame_types:
            return []
        out: list[Diagnostic] = []
        aliases = _key_aliases(ctx.tree, "type")
        chain_members: set[int] = set()  # If nodes consumed as elif arms
        for node in ast.walk(ctx.tree):
            # Header dict literals: {"type": "x", ...}
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant) and k.value == "type"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and v.value not in regs.frame_types
                    ):
                        out.append(
                            Diagnostic(
                                ctx.relpath, v.lineno, v.col_offset, "DS801",
                                f"frame type {v.value!r} is not registered "
                                f"in {ctx.config.proto_registry_path}",
                            )
                        )
            # == comparisons (chain arms handled below; lone compares too).
            elif isinstance(node, ast.Compare):
                lit = _eq_literal(node, "type", aliases)
                if lit is not None and lit not in regs.frame_types:
                    out.append(
                        Diagnostic(
                            ctx.relpath, node.lineno, node.col_offset,
                            "DS801",
                            f"comparison against unregistered frame type "
                            f"{lit!r} is a dead branch (not in "
                            f"{ctx.config.proto_registry_path})",
                        )
                    )
            # Dispatch chains: if t == "a": ... elif t == "b": ... [else]
            elif isinstance(node, ast.If) and id(node) not in chain_members:
                handled: list[str] = []
                cur: ast.If | None = node
                has_default = False
                while cur is not None:
                    lit = _eq_literal(cur.test, "type", aliases)
                    if lit is None:
                        # A non-frame test inside the chain acts as a
                        # default arm (it can route anything else).
                        has_default = bool(handled)
                        break
                    handled.append(lit)
                    if len(cur.orelse) == 1 and isinstance(
                        cur.orelse[0], ast.If
                    ):
                        cur = cur.orelse[0]
                        chain_members.add(id(cur))
                    else:
                        has_default = bool(cur.orelse)
                        cur = None
                # A dispatch is a chain of >= 2 arms; a lone equality test
                # is a guard (e.g. checking one expected reply type), not a
                # coverage surface.
                if len(handled) >= 2 and not has_default:
                    missing = sorted(
                        set(regs.frame_types) - set(handled)
                    )
                    if missing:
                        out.append(
                            Diagnostic(
                                ctx.relpath, node.lineno, node.col_offset,
                                "DS802",
                                "receive dispatch handles "
                                f"{sorted(set(handled))} but registered "
                                f"frame types {missing} fall through "
                                "silently; add the arms or an explicit "
                                "default (else) branch",
                            )
                        )
        return out

    # -- DS803 ---------------------------------------------------------------

    def _check_reasons(self, ctx, regs) -> list[Diagnostic]:
        if not regs.admission_reasons:
            return []
        # The vocabulary module itself only *defines* the reasons.
        if ctx.relpath == ctx.config.admission_registry_path.replace("\\", "/"):
            return []
        out: list[Diagnostic] = []
        for node in ast.walk(ctx.tree):
            lit = None
            if isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                    left, right = node.left, node.comparators[0]
                    is_reason = (
                        isinstance(left, ast.Attribute)
                        and left.attr == "reason"
                    ) or _type_key_expr(left, "reason")
                    if (
                        is_reason
                        and isinstance(right, ast.Constant)
                        and isinstance(right.value, str)
                    ):
                        lit = right.value
            elif isinstance(node, ast.Call):
                callee = node.func
                name = (
                    callee.attr if isinstance(callee, ast.Attribute)
                    else getattr(callee, "id", None)
                )
                if name == "Admission":
                    if len(node.args) >= 2 and isinstance(
                        node.args[1], ast.Constant
                    ) and isinstance(node.args[1].value, str):
                        lit = node.args[1].value
                    for kw in node.keywords:
                        if (
                            kw.arg == "reason"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)
                        ):
                            lit = kw.value.value
            if lit is not None and lit not in regs.admission_reasons:
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS803",
                        f"admission reason {lit!r} is not registered in "
                        f"{ctx.config.admission_registry_path}",
                    )
                )
        return out
