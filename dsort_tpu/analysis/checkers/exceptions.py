"""Recovery-path exception hygiene.

The reference detects failure exclusively through error returns (PAPER.md
§5.3 — ``send()/recv() <= 0``); the rebuild routes failures through typed
exceptions, which means one overbroad ``except`` in a recovery path can
silently eat the very signal the fault machinery exists to observe.  On the
files that implement recovery (schedulers, fault classification, checkpoint
store, multihost resume, the CLI's job loops) this checker enforces: catch
narrowly, or visibly account for what you swallowed.

  DS401  bare ``except:`` — also catches ``KeyboardInterrupt``/
         ``SystemExit``; allowed only when the body re-raises
  DS402  ``except Exception``/``BaseException`` whose handler neither
         re-raises, nor returns/continues control flow deliberately
         (``return``/``continue``/``break``), nor reports (journal
         ``.event``/``.emit``/``.bump``, ``log.*``, ``warnings.warn``,
         raising a new error)

``__del__`` bodies are exempt: swallowing during interpreter teardown is
the documented idiom there.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

_BROAD = {"Exception", "BaseException"}
_REPORT_ATTRS = {
    "emit", "bump", "event", "debug", "info", "warning", "error",
    "exception", "critical", "warn", "print_exc",
}


def _is_broad(type_expr: ast.expr | None) -> bool:
    if type_expr is None:
        return False  # bare except handled separately
    exprs = (
        type_expr.elts if isinstance(type_expr, ast.Tuple) else [type_expr]
    )
    for e in exprs:
        name = e.attr if isinstance(e, ast.Attribute) else getattr(e, "id", None)
        if name in _BROAD:
            return True
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler visibly deals with the error: re-raises,
    changes control flow on purpose, reports it, or propagates the bound
    exception VALUE somewhere (``box["e"] = e`` — the lane-thread error
    relay pattern)."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Continue, ast.Break)):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REPORT_ATTRS
        ):
            return True
        if (
            handler.name is not None
            and isinstance(node, ast.Name)
            and node.id == handler.name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


class ExceptionsChecker(Checker):
    name = "exceptions"
    codes = {
        "DS401": "bare except in a recovery path",
        "DS402": "overbroad except swallows errors without reporting",
    }
    scope = (
        "dsort_tpu/scheduler/*.py",
        "dsort_tpu/checkpoint.py",
        "dsort_tpu/parallel/distributed.py",
        "dsort_tpu/cli.py",
        "dsort_tpu/runtime/*.py",
    )

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        exempt: set[int] = set()  # handler nodes inside __del__
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "__del__":
                for inner in ast.walk(node):
                    if isinstance(inner, ast.ExceptHandler):
                        exempt.add(id(inner))
        out: list[Diagnostic] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler) or id(node) in exempt:
                continue
            if node.type is None and not _reraises(node):
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS401",
                        "bare 'except:' in a recovery path catches "
                        "KeyboardInterrupt/SystemExit too; name the "
                        "exception types (and report what you swallow)",
                    )
                )
            elif _is_broad(node.type) and not _handles(node):
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS402",
                        "broad 'except Exception' swallows the error with no "
                        "re-raise, no fault event, and no log — a failure "
                        "here would vanish from the fault timeline",
                    )
                )
        return out
