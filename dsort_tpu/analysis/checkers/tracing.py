"""Tracing-hygiene checker for jit / shard_map / Pallas code.

A side effect inside a traced function does not do what its author meant:
it fires once at TRACE time (then never again, however many times the
compiled program runs), or — for journal emits — records an event that
claims a device did work it may never do.  The rebuild's event journal makes
this an easy trap: ``metrics.event`` is one attribute access away from any
function, and under ``jit`` it would silently journal at compile time.

The checker builds each module's TRACED SET — functions decorated with
``jit`` (including ``functools.partial(jax.jit, ...)``), functions passed to
``jit``/``shard_map``/``pallas_call`` (directly, via a local alias, or
wrapped in ``functools.partial``), lambdas traced inline, plus the
transitive closure over same-module calls — and flags:

  DS301  host side effect under trace: ``print``/``open``/``input``,
         ``time.*`` clock reads, journal/metrics emission (``.emit`` /
         ``.bump`` / ``.event``), logging calls, host randomness
         (``random.*`` / ``np.random.*``), or ``global``/``nonlocal``
         declarations
  DS302  a non-static value reaches a Pallas kernel's launch geometry: a
         ``pallas_call`` ``grid=``/``out_shape=`` expression references a
         parameter of the enclosing jit function that is not listed in
         ``static_argnames`` (shapes/dtypes of traced arrays are fine —
         they are static under jit; the VALUE of a traced scalar is not)

Cross-module calls are not followed (each module is checked on its own
terms); trace-time *configuration* shims (``utils.compat.enable_x64``) are
deliberately not treated as side effects — they exist to steer tracing.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename as _callee_basename
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

#: Callee names that enter a tracing context; the first positional argument
#: is (or resolves to) the traced callable.
_TRACING_ENTRY = {"jit", "shard_map", "pallas_call"}

#: Receiver attribute calls that emit/journal (side effects under trace).
_EMIT_ATTRS = {"emit", "bump", "event", "ingest"}
_LOG_ATTRS = {"debug", "info", "warning", "error", "exception", "critical"}
_LOG_RECEIVERS = {"log", "logger", "logging"}
_CLOCK_ATTRS = {"time", "monotonic", "perf_counter", "process_time", "sleep"}
_BUILTIN_EFFECTS = {"print", "open", "input"}
_STATIC_OK_ATTRS = {"shape", "dtype", "ndim", "size"}


def _is_partial(call: ast.Call) -> bool:
    return isinstance(call, ast.Call) and _callee_basename(call.func) == "partial"


def _target_of(expr: ast.expr, local_aliases: dict) -> ast.expr | None:
    """Resolve a traced-callable expression to a Name/Lambda if possible."""
    seen = 0
    while seen < 8:
        seen += 1
        if isinstance(expr, ast.Call) and _is_partial(expr):
            if not expr.args:
                return None
            expr = expr.args[0]
        elif isinstance(expr, ast.Name) and expr.id in local_aliases:
            expr = local_aliases[expr.id]
        else:
            break
    return expr if isinstance(expr, (ast.Name, ast.Lambda)) else None


def _jit_static_names(deco_or_call: ast.Call) -> set[str] | None:
    """``static_argnames`` of a jit decorator/call, or None if not a jit."""
    if _callee_basename(deco_or_call.func) == "partial":
        if not deco_or_call.args:
            return None
        inner = deco_or_call.args[0]
        if _callee_basename(inner) != "jit":
            return None
        kws = deco_or_call.keywords
    elif _callee_basename(deco_or_call.func) == "jit":
        kws = deco_or_call.keywords
    else:
        return None
    for kw in kws:
        if kw.arg == "static_argnames":
            names = set()
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
            return names
    return set()


class TracingChecker(Checker):
    name = "tracing"
    codes = {
        "DS301": "host side effect inside a traced (jit/shard_map/pallas) "
                 "function",
        "DS302": "non-static value in a pallas_call grid/out_shape",
    }
    scope = ("*.py",)

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        module_fns: dict[str, ast.FunctionDef] = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.FunctionDef)
        }
        traced: dict[str, ast.FunctionDef] = {}
        traced_lambdas: dict[int, ast.Lambda] = {}  # id() -> node: the
        # module-wide and per-function seeding walks both reach inline
        # lambdas; keying by node identity keeps each reported once
        jit_statics: dict[str, set[str]] = {}

        # Seed 1: decorated functions.
        for fn in module_fns.values():
            for deco in fn.decorator_list:
                base = deco
                if isinstance(deco, ast.Call):
                    statics = _jit_static_names(deco)
                    if statics is not None:
                        traced[fn.name] = fn
                        jit_statics[fn.name] = statics
                        continue
                    base = deco.func
                if _callee_basename(base) in _TRACING_ENTRY:
                    traced[fn.name] = fn
                    jit_statics.setdefault(fn.name, set())

        # Seed 2: callables handed to jit/shard_map/pallas_call anywhere.
        # Local aliases (fn = functools.partial(F, ...)) resolve per
        # enclosing function body.
        def seed_calls(body_owner, local_aliases):
            for node in ast.walk(body_owner):
                if not isinstance(node, ast.Call):
                    continue
                if _callee_basename(node.func) not in _TRACING_ENTRY:
                    continue
                if not node.args:
                    continue
                tgt = _target_of(node.args[0], local_aliases)
                if isinstance(tgt, ast.Lambda):
                    traced_lambdas[id(tgt)] = tgt
                elif isinstance(tgt, ast.Name) and tgt.id in module_fns:
                    fn = module_fns[tgt.id]
                    traced.setdefault(tgt.id, fn)
                    if _callee_basename(node.func) == "jit":
                        statics = _jit_static_names(node) or set()
                        jit_statics.setdefault(tgt.id, statics)

        def simple_assigns(owner) -> dict[str, ast.expr]:
            out: dict[str, ast.expr] = {}
            for node in ast.walk(owner):
                if isinstance(node, ast.Assign) and isinstance(
                    node.targets[0], ast.Name
                ):
                    out.setdefault(node.targets[0].id, node.value)
            return out

        module_aliases = {
            t.id: n.value
            for n in ctx.tree.body
            if isinstance(n, ast.Assign)
            for t in n.targets
            if isinstance(t, ast.Name)
        }
        seed_calls(ctx.tree, module_aliases)
        for fn in module_fns.values():
            # Re-seed with the function's OWN aliases so a local
            # `f = functools.partial(shard_fn, ...)` resolves correctly even
            # when another function reuses the name for something else.
            seed_calls(fn, {**module_aliases, **simple_assigns(fn)})

        # Transitive closure over same-module calls from traced bodies.
        work = list(traced.values())
        while work:
            fn = work.pop()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name
                ):
                    callee = module_fns.get(node.func.id)
                    if callee is not None and callee.name not in traced:
                        traced[callee.name] = callee
                        work.append(callee)

        diags: list[Diagnostic] = []
        for name, fn in traced.items():
            diags.extend(self._effects(ctx, fn, f"traced function {name!r}"))
            diags.extend(
                self._pallas_geometry(ctx, fn, jit_statics.get(name))
            )
        for lam in traced_lambdas.values():
            diags.extend(self._effects(ctx, lam, "traced lambda"))
        return diags

    def _effects(self, ctx, fn, label) -> list[Diagnostic]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS301",
                        f"{kind} state mutation inside {label} runs at trace "
                        "time, not per execution",
                    )
                )
            if not isinstance(node, ast.Call):
                continue
            what = self._effect_call(node)
            if what is not None:
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS301",
                        f"{what} inside {label} fires once at trace time "
                        "(and journals compile-time state, not execution)",
                    )
                )
        return out

    @staticmethod
    def _effect_call(node: ast.Call) -> str | None:
        f = node.func
        if isinstance(f, ast.Name) and f.id in _BUILTIN_EFFECTS:
            return f"call to {f.id}()"
        if not isinstance(f, ast.Attribute):
            return None
        recv = f.value
        recv_name = recv.id if isinstance(recv, ast.Name) else None
        if recv_name == "time" and f.attr in _CLOCK_ATTRS:
            return f"clock read time.{f.attr}()"
        if f.attr in _EMIT_ATTRS and recv_name != "self":
            # metrics.event / journal.emit / metrics.bump — journaling.
            return f"journal emission .{f.attr}()"
        if recv_name in _LOG_RECEIVERS and f.attr in _LOG_ATTRS:
            return f"logging call {recv_name}.{f.attr}()"
        if recv_name == "random":
            return f"host randomness random.{f.attr}()"
        if (
            isinstance(recv, ast.Attribute)
            and recv.attr == "random"
            and isinstance(recv.value, ast.Name)
            and recv.value.id in ("np", "numpy")
        ):
            return f"host randomness {recv.value.id}.random.{f.attr}()"
        return None

    def _pallas_geometry(self, ctx, fn, statics) -> list[Diagnostic]:
        """DS302: pallas_call grid/out_shape using a non-static parameter."""
        if statics is None or isinstance(fn, ast.Lambda):
            return []  # only meaningful when the jit static set is known
        params = {
            a.arg
            for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs)
        }
        simple_locals: dict[str, ast.expr] = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                simple_locals.setdefault(node.targets[0].id, node.value)
        out = []
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and _callee_basename(node.func) == "pallas_call"
            ):
                continue
            for kw in node.keywords:
                if kw.arg not in ("grid", "out_shape"):
                    continue
                for name_node in self._value_names(kw.value, simple_locals):
                    if name_node.id in params and name_node.id not in statics:
                        out.append(
                            Diagnostic(
                                ctx.relpath, name_node.lineno,
                                name_node.col_offset, "DS302",
                                f"pallas_call {kw.arg}= uses parameter "
                                f"{name_node.id!r}, which is traced (not in "
                                "static_argnames) — kernel geometry must be "
                                "static",
                            )
                        )
        return out

    def _value_names(self, expr: ast.expr, simple_locals, depth=0):
        """Names whose runtime VALUE feeds ``expr``.

        Two exclusions keep this honest: ``x.shape``/``x.dtype`` accessors
        are static under jit, and names passed as arguments to helper CALLS
        (``out_shape=_shapes(xs)``) are assumed shape-only plumbing — except
        for ``ShapeDtypeStruct(...)``, whose arguments ARE the geometry and
        stay checked.  One level of simple-local resolution.
        """
        static_bases: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_OK_ATTRS:
                for inner in ast.walk(node.value):
                    static_bases.add(id(inner))
            elif (
                isinstance(node, ast.Call)
                and _callee_basename(node.func) != "ShapeDtypeStruct"
            ):
                for sub in node.args + [kw.value for kw in node.keywords]:
                    for inner in ast.walk(sub):
                        static_bases.add(id(inner))
        for node in ast.walk(expr):
            if not isinstance(node, ast.Name) or id(node) in static_bases:
                continue
            if depth < 1 and node.id in simple_locals:
                yield from self._value_names(
                    simple_locals[node.id], simple_locals, depth + 1
                )
            else:
                yield node
