"""Durability-discipline checker for the persisted-state writers.

The restart contracts (controller state+spool, checkpoint shards/ranges/
wave runs, flight bundles) all rest on one idiom: write to a tmp name,
fsync, then ``os.replace`` into place — so a reader never sees a torn
file and a rename never publishes bytes the disk may still lose.  PR 12
burned review rounds hand-catching exactly these shapes (a torn
non-atomic spool write, persist IO under the controller lock); this
checker pins them statically for every future writer.

Codes
  DS701  a write-mode ``open()`` / ``np.save`` targets a final (non-tmp)
         path: a crash mid-write leaves a torn file where recovery
         expects a whole one.  Tmp-shaped targets — a name containing
         ``tmp`` or an expression building a ``".tmp"`` suffix — are the
         sanctioned first half of the idiom.  ``open(path, "wb").close()``
         (the create/truncate "touch") writes no payload and is exempt.
  DS702  ``os.replace``/``os.rename`` publishes a file this function wrote
         with no fsync in between: the rename can land while the data is
         still only in the page cache, so a listed-complete file may be
         empty after power loss.  Any call whose name contains ``fsync``
         (including project fsync helpers) satisfies the idiom.
  DS703  persist IO (write-open, ``np.save``, rename, fsync, journal
         ``flush_jsonl``) while holding a SHARED lock — one acquired in
         two or more functions of the module.  Disk latency must never
         serialize a control plane: snapshot under the lock, write
         outside it.  A dedicated single-function flush lock (the
         seq-guarded flusher pattern) is the sanctioned shape and is not
         flagged.

Static limits, stated so suppressions stay honest: only direct calls in
the inspected function are seen (a helper that writes for a lock-holding
caller is invisible — same doctrine as DS202), and tmp-ness is a naming
convention, not a data-flow proof.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename as _callee_basename
from dsort_tpu.analysis.astutil import own_nodes as _own_nodes
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_WRITE_MODES = ("w", "x", "a")


def _is_write_open(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and any(ch in mode.value for ch in _WRITE_MODES)
    )


def _is_np_save(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "save"
        and isinstance(f.value, ast.Name)
        and f.value.id in ("np", "numpy")
    )


def _is_rename(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("replace", "rename")
        and isinstance(f.value, ast.Name)
        and f.value.id == "os"
    )


def _is_fsync(call: ast.Call) -> bool:
    name = _callee_basename(call.func)
    return name is not None and "fsync" in name


def _is_persist_io(call: ast.Call) -> bool:
    if _is_write_open(call) or _is_np_save(call) or _is_rename(call):
        return True
    if _is_fsync(call):
        return True
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr == "flush_jsonl"


def _expr_has_tmp(expr: ast.expr) -> bool:
    """True when the expression builds a tmp-shaped path: a name containing
    ``tmp`` or any string piece containing ``.tmp``."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "tmp" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and "tmp" in node.attr.lower():
            return True
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and ".tmp" in node.value
        ):
            return True
    return False


def _target_name(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class DurabilityChecker(Checker):
    name = "durability"
    codes = {
        "DS701": "raw write to a persisted-state path (no tmp+rename)",
        "DS702": "rename publishes written data without a preceding fsync",
        "DS703": "persist IO while holding a shared lock",
    }
    #: The persisted-state writers.  `utils/events.py` (the journal) is
    #: deliberately out of scope: it is an append-structured log with its
    #: own rotation contract, not recovery state a resume trusts.
    scope = (
        "dsort_tpu/checkpoint.py",
        "dsort_tpu/fleet/*.py",
        "dsort_tpu/serve/*.py",
        "dsort_tpu/models/wave_sort.py",
        "dsort_tpu/models/external_sort.py",
        "dsort_tpu/obs/flight.py",
    )

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        diags: list[Diagnostic] = []
        fns = [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        shared_locks = self._shared_locks(ctx, fns)
        for fn in fns:
            diags.extend(self._check_function(ctx, fn, shared_locks))
        return diags

    # -- DS703 lock census ---------------------------------------------------

    def _shared_locks(self, ctx, fns) -> set[tuple]:
        """Lock identities acquired in >= 2 functions of this module (the
        coordination locks persist IO must never run under)."""
        known: set[tuple] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and _callee_basename(node.value.func) in _LOCK_FACTORIES
            ):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    known.add(("global", t.id))
                elif isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ):
                    known.add(("attr", t.attr))
        users: dict[tuple, set[str]] = {}
        for fn in fns:
            for node in _own_nodes(fn):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    lock = self._lock_id(item.context_expr, known)
                    if lock is not None:
                        users.setdefault(lock, set()).add(fn.name)
        return {lock for lock, fns_using in users.items() if len(fns_using) >= 2}

    @staticmethod
    def _lock_id(expr: ast.expr, known: set[tuple]) -> tuple | None:
        if isinstance(expr, ast.Name) and ("global", expr.id) in known:
            return ("global", expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and ("attr", expr.attr) in known
        ):
            return ("attr", expr.attr)
        return None

    # -- per-function scan ---------------------------------------------------

    def _check_function(self, ctx, fn, shared_locks) -> list[Diagnostic]:
        diags: list[Diagnostic] = []
        writes: dict[str, int] = {}  # target name -> first write line
        fsync_lines: list[int] = []
        renames: list[tuple[str | None, int, int]] = []
        # Calls whose result is immediately .close()d write nothing (the
        # create/truncate touch idiom).
        touch_ids = {
            id(node.func.value)
            for node in _own_nodes(fn)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "close"
            and isinstance(node.func.value, ast.Call)
        }
        # File handles bound from a write-open (`with open(tmp, "w") as f:`
        # or `f = open(tmp, "w")`): writes THROUGH the handle (np.save(f),
        # json.dump(..., f)) were already judged at the open site.
        handle_names: set[str] = set()
        for node in _own_nodes(fn):
            if isinstance(node, ast.withitem):
                if (
                    isinstance(node.context_expr, ast.Call)
                    and _is_write_open(node.context_expr)
                    and isinstance(node.optional_vars, ast.Name)
                ):
                    handle_names.add(node.optional_vars.id)
            elif isinstance(node, ast.Assign):
                if (
                    isinstance(node.value, ast.Call)
                    and _is_write_open(node.value)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    handle_names.add(node.targets[0].id)
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_write_open(node) or _is_np_save(node):
                target = node.args[0] if node.args else None
                if target is None:
                    continue
                name = _target_name(target)
                if name is not None and name in handle_names and _is_np_save(node):
                    continue  # np.save through an open handle: judged at open
                if name is not None:
                    writes.setdefault(name, node.lineno)
                if id(node) in touch_ids:
                    continue
                if not _expr_has_tmp(target):
                    what = "np.save" if _is_np_save(node) else "open"
                    diags.append(
                        Diagnostic(
                            ctx.relpath, node.lineno, node.col_offset, "DS701",
                            f"{what} writes a persisted-state path directly; "
                            "a crash mid-write leaves a torn file — write a "
                            "tmp name, fsync, then os.replace into place",
                        )
                    )
            elif _is_fsync(node):
                fsync_lines.append(node.lineno)
            elif _is_rename(node):
                src = node.args[0] if node.args else None
                renames.append(
                    (_target_name(src) if src is not None else None,
                     node.lineno, node.col_offset)
                )
        for src_name, line, col in renames:
            if src_name is None or src_name not in writes:
                continue  # renaming something this function did not write
            # The fsync must land BETWEEN this file's write and its rename:
            # an earlier fsync belonging to a previous publish in the same
            # function must not bless a later unsynced one.
            if not any(writes[src_name] <= fl < line for fl in fsync_lines):
                diags.append(
                    Diagnostic(
                        ctx.relpath, line, col, "DS702",
                        f"os.replace publishes {src_name!r} without a "
                        "preceding fsync — the rename can land before the "
                        "data is durable (tmp+fsync+rename)",
                    )
                )
        diags.extend(self._io_under_lock(ctx, fn, shared_locks))
        return diags

    def _io_under_lock(self, ctx, fn, shared_locks) -> list[Diagnostic]:
        diags: list[Diagnostic] = []

        def flag(node, held):
            label = held[1] if held[0] == "global" else f"self.{held[1]}"
            diags.append(
                Diagnostic(
                    ctx.relpath, node.lineno, node.col_offset, "DS703",
                    f"persist IO under shared lock {label}: disk latency "
                    "serializes every other holder — snapshot under the "
                    "lock, write outside it",
                )
            )

        def scan_expr(expr, held):
            if held is None:
                return
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and _is_persist_io(node):
                    flag(node, held)

        def scan(nodes, held: tuple | None):
            for node in nodes:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(node, ast.With):
                    inner_held = held
                    for item in node.items:
                        # The context expression itself (e.g. `with
                        # open(tmp, "w") as f:`) evaluates under the OUTER
                        # lock state.
                        scan_expr(item.context_expr, held)
                        lock = self._lock_id(item.context_expr, shared_locks)
                        if lock is not None:
                            inner_held = lock
                    scan(node.body, inner_held)
                    continue
                if isinstance(node, ast.expr):
                    scan_expr(node, held)
                    continue
                # Statements: flag their own expressions, recurse into
                # nested statement blocks (if/for/try bodies keep the
                # current lock state).
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.expr):
                        scan_expr(child, held)
                    elif isinstance(child, (ast.stmt, ast.excepthandler)):
                        scan([child], held)

        scan(fn.body, None)
        return diags
