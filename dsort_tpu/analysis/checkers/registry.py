"""Registry-coverage checker: the event/counter vocabulary cannot drift.

`EventLog.emit` refuses unregistered types at RUNTIME — but only on the
code path that actually runs, which for fault events is exactly the path
that almost never runs (the PR 1 fault drills exist because of this).  This
checker moves the guarantee to lint time, and extends it across the
language boundary: the C++ coordinator's event lines are scanned out of
``coordinator.cpp`` with a small lexer and resolved against the same
registry plus the Python-side parser map, so a name added on one side
without the other fails ``dsort lint`` before any cluster exists.

Codes (example names single-quoted so the registry-exhaustiveness test's
own source grep — double-quoted literals — never reads this docstring)
  DS101  Python ``.emit('x', ...)`` / ``.event('x', ...)`` /
         ``.ingest(t, mono, 'x', ...)`` name not in ``EVENT_TYPES``
  DS102  Python ``.bump('x', ...)`` name not in ``COUNTERS``
  DS103  C++ ``log_event_locked("x", ...)`` name not in ``EVENT_TYPES``
  DS104  C++ event name missing from ``runtime/native.py``'s
         ``_COORD_EVENT_TYPES`` parser map (the line would be silently
         dropped on drain)
  DS105  a registry source file could not be read (configuration error)
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext
from dsort_tpu.analysis import cpp_lexer

#: Method name -> (positional index of the name argument, registry attr,
#: diagnostic code).  ``ingest`` carries (t, mono, etype, ...).
_EVENT_METHODS = {
    "emit": (0, "event_types", "DS101"),
    "event": (0, "event_types", "DS101"),
    "ingest": (2, "event_types", "DS101"),
    "bump": (0, "counters", "DS102"),
}


class RegistryChecker(Checker):
    name = "registry"
    codes = {
        "DS101": "event type not registered in utils.events.EVENT_TYPES",
        "DS102": "counter name not registered in utils.events.COUNTERS",
        "DS103": "native event name not registered in EVENT_TYPES",
        "DS104": "native event name absent from the drain parser map",
        "DS105": "registry source file unreadable",
    }
    scope = ("*.py", "*.cpp", "*.cc")

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        regs = ctx.registries.load()
        # DS105 anchors on the MISSING REGISTRY path, not the visited file:
        # identical diagnostics collapse in the engine's dedup, so one
        # misconfigured registry_path reports once per run, not per file.
        diags = [
            Diagnostic(miss.replace("\\", "/"), 1, 0, "DS105",
                       "cannot read registry source (check "
                       "[tool.dsort.lint] registry/native_map paths)")
            for miss in regs.missing
        ]
        if ctx.is_python:
            diags.extend(self._check_python(ctx, regs))
        else:
            diags.extend(self._check_cpp(ctx, regs))
        return diags

    def _check_python(self, ctx: FileContext, regs) -> list[Diagnostic]:
        # The registry definition module itself only *defines* names.
        if ctx.relpath == ctx.config.registry_path.replace("\\", "/"):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            spec = _EVENT_METHODS.get(node.func.attr)
            if spec is None:
                continue
            idx, attr, code = spec
            if len(node.args) <= idx:
                continue
            arg = node.args[idx]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue  # dynamic names are guarded at runtime by EventLog
            registry = getattr(regs, attr)
            if registry and arg.value not in registry:
                kind = "counter" if attr == "counters" else "event type"
                out.append(
                    Diagnostic(
                        ctx.relpath, arg.lineno, arg.col_offset, code,
                        f"{kind} {arg.value!r} is not registered in "
                        f"{ctx.config.registry_path}",
                    )
                )
        return out

    def _check_cpp(self, ctx: FileContext, regs) -> list[Diagnostic]:
        out = []
        for tok in cpp_lexer.call_string_args(ctx.source, "log_event_locked"):
            if regs.event_types and tok.value not in regs.event_types:
                out.append(
                    Diagnostic(
                        ctx.relpath, tok.line, 0, "DS103",
                        f"native event {tok.value!r} is not registered in "
                        f"{ctx.config.registry_path}",
                    )
                )
            elif regs.native_map and tok.value not in regs.native_map:
                out.append(
                    Diagnostic(
                        ctx.relpath, tok.line, 0, "DS104",
                        f"native event {tok.value!r} is missing from "
                        f"_COORD_EVENT_TYPES in {ctx.config.native_map_path}; "
                        "drained lines of this type would be dropped",
                    )
                )
        return out
