"""Concurrency-discipline checker for the lock-protected hot structures.

The reference's liveness array is read/written by every thread with no lock
(SURVEY.md §5.2 calls the race out); the rebuild's `WorkerTable`, `Metrics`,
`EventLog`, lane registries and C++-mirror driver state are lock-protected
by construction — but nothing verified that every NEW mutation site kept the
discipline.  This checker infers each class's (and module's) lock-guarded
state and flags drift:

  DS201  a lock-guarded attribute (one that is mutated under ``with lock:``
         somewhere) is mutated OUTSIDE any lock block (``__init__``/module
         top level excluded — single-threaded construction)
  DS202  a blocking call (``sleep``/``join``/``recv``/``wait``/subprocess
         waits/``accept``/``select``/``input``) is made while holding a
         lock — the shape that turns one slow worker into a stalled
         scheduler.  ``.wait()`` on the held object itself (the condition-
         variable pattern) is allowed.
  DS203  two locks are acquired in both nesting orders in one module — the
         classic ABBA deadlock

Static inference has limits, stated here so suppressions stay honest: only
DIRECT calls inside a ``with`` block are seen (a helper that sleeps while
its caller holds a lock is invisible), and "mutation" means assignment,
augmented assignment, ``del``, or calling a known mutator method
(``append``/``pop``/``update``/...) on the attribute.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

#: Expressions whose call constructs a lock-like object.
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

#: Method names that mutate their receiver in place.
_MUTATORS = {
    "append", "extend", "insert", "add", "discard", "remove", "clear",
    "update", "pop", "popleft", "popitem", "appendleft", "setdefault",
}

#: Callee names that block the calling thread.
_BLOCKING_ATTRS = {
    "sleep", "join", "recv", "recv_into", "accept", "wait", "wait_for",
    "communicate", "select",
}
_BLOCKING_NAMES = {"input", "sleep"}
_BLOCKING_DOTTED = {
    ("time", "sleep"), ("select", "select"), ("subprocess", "run"),
    ("subprocess", "call"), ("subprocess", "check_call"),
    ("subprocess", "check_output"),
}


def _is_lock_factory(value: ast.expr) -> bool:
    """True for ``threading.Lock()`` / ``Lock()`` / ``field(default_factory=
    threading.Lock)`` shapes."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
    if name in _LOCK_FACTORIES:
        return True
    if name == "field":  # dataclasses.field(default_factory=threading.Lock)
        for kw in value.keywords:
            if kw.arg == "default_factory":
                g = kw.value
                gname = (
                    g.attr if isinstance(g, ast.Attribute)
                    else getattr(g, "id", None)
                )
                if gname in _LOCK_FACTORIES:
                    return True
    return False


def _expr_lock_id(
    expr: ast.expr, self_name: str | None, known: set, owner: str | None
) -> tuple | None:
    """Resolve a ``with`` context expression to a known lock identity.

    ``("attr", owner_class, name)`` for ``self.<name>`` — qualified by the
    owning class so two classes' same-named locks (every class calls its
    lock ``_lock``) never alias in the DS203 order graph; ``("global",
    name)`` for a module-level lock.  None when the expression is no known
    lock.
    """
    if (
        self_name is not None
        and isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == self_name
        and ("attr", owner, expr.attr) in known
    ):
        return ("attr", owner, expr.attr)
    if isinstance(expr, ast.Name) and ("global", expr.id) in known:
        return ("global", expr.id)
    return None


def _lock_label(lock: tuple) -> str:
    return lock[1] if lock[0] == "global" else f"self.{lock[2]}"


def _mutation_roots(node: ast.stmt, self_name: str | None, declared: set[str]):
    """Yield ``(kind, name, anchor)`` for state mutated by one statement.

    kind is "attr" (``self.<name>`` or a mutator-method call on it) or
    "global" (module-level name).  A bare-name rebind (``x = ...``) only
    counts as a global mutation when the function declared ``global x`` —
    otherwise it is a local variable.  Only the statement's own
    targets/calls are inspected — nested statements get their own visit.
    """

    def root(expr):
        # Peel subscripts: self.x[i] mutates x; NAME[i] mutates NAME.
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        if (
            self_name is not None
            and isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == self_name
        ):
            return ("attr", expr.attr, expr)
        if isinstance(expr, ast.Name):
            return ("global", expr.id, expr)
        return None

    targets: list[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    for t in targets:
        for el in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
            r = root(el)
            if r is None:
                continue
            if r[0] == "global" and isinstance(el, ast.Name):
                if el.id in declared:  # plain rebinds are locals otherwise
                    yield r
            else:
                yield r
    # Mutator-method calls in SIMPLE statements only: compound statements
    # (if/for/try) carry nested statement lists whose own visits would
    # double-report anything found by walking them from here.
    if isinstance(
        node, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
               ast.Return, ast.Delete)
    ):
        for call in ast.walk(node):
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in _MUTATORS
            ):
                r = root(call.func.value)
                if r:
                    yield r


def _blocking_call(call: ast.Call, held_exprs: list[ast.expr]) -> str | None:
    """Name of the blocking operation if ``call`` blocks, else None."""
    f = call.func
    if isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES:
        return f.id
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and (f.value.id, f.attr) in _BLOCKING_DOTTED:
            return f"{f.value.id}.{f.attr}"
        if f.attr in _BLOCKING_ATTRS:
            # Condition-variable pattern: obj.wait() while holding obj.
            for held in held_exprs:
                if ast.dump(f.value) == ast.dump(held):
                    return None
            return f.attr
    return None


class _ScopeScan(ast.NodeVisitor):
    """Scan one function body tracking the stack of held locks."""

    def __init__(self, checker, ctx, self_name, known_locks, fn_name, sink,
                 declared=(), owner=None):
        self.checker = checker
        self.ctx = ctx
        self.self_name = self_name
        self.known = known_locks
        self.fn_name = fn_name
        self.sink = sink  # records (event, payload) tuples
        self.declared = set(declared)  # names under a `global` statement
        self.owner = owner  # owning class name for attr locks
        self.held: list[tuple] = []  # lock ids, outermost first
        self.held_exprs: list[ast.expr] = []

    # Nested defs run on other stacks (threads/late calls): their bodies are
    # scanned as separate scopes by the checker, not under this lock stack.
    def visit_FunctionDef(self, node):  # noqa: N802
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        return

    def visit_With(self, node):  # noqa: N802
        acquired = []
        for item in node.items:
            lock = _expr_lock_id(
                item.context_expr, self.self_name, self.known, self.owner
            )
            if lock is not None:
                for outer in self.held:
                    self.sink.append(
                        ("order", (outer, lock, self.ctx.relpath,
                                   item.context_expr.lineno))
                    )
                self.held.append(lock)
                self.held_exprs.append(item.context_expr)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()
            self.held_exprs.pop()

    def visit_Call(self, node):  # noqa: N802
        if self.held:
            op = _blocking_call(node, self.held_exprs)
            if op is not None:
                self.sink.append(
                    ("blocking", (op, self.held[-1], node.lineno,
                                  node.col_offset))
                )
        self.generic_visit(node)

    def generic_visit(self, node):
        if isinstance(node, ast.stmt):
            for kind, name, anchor in _mutation_roots(
                node, self.self_name, self.declared
            ):
                self.sink.append(
                    ("mutation", (kind, name, bool(self.held),
                                  anchor.lineno, anchor.col_offset,
                                  self.fn_name))
                )
        super().generic_visit(node)


class ConcurrencyChecker(Checker):
    name = "concurrency"
    codes = {
        "DS201": "lock-guarded attribute mutated outside its lock",
        "DS202": "blocking call while holding a lock",
        "DS203": "inconsistent lock acquisition order (ABBA)",
    }
    scope = ("*.py",)

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        diags: list[Diagnostic] = []
        order_edges: dict[tuple, tuple] = {}  # (A, B) -> first location
        module_locks = {
            ("global", t.id)
            for node in ctx.tree.body
            if isinstance(node, ast.Assign) and _is_lock_factory(node.value)
            for t in node.targets
            if isinstance(t, ast.Name)
        }
        # Module-level functions form one scope over the module locks;
        # each class forms a scope over self-attribute locks + module locks.
        scopes: list[tuple[list[ast.FunctionDef], set, str | None]] = []
        mod_fns = [
            n for n in ctx.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        scopes.append((mod_fns, module_locks, None))
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                scopes.append(self._class_scope(node, module_locks))
        for fns, locks, owner in scopes:
            diags.extend(
                self._scan_scope(ctx, fns, locks, owner, order_edges)
            )
        return diags

    def _class_scope(self, cls: ast.ClassDef, module_locks):
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        locks = set(module_locks)
        for stmt in cls.body:  # dataclass-style class-level lock fields
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                if (stmt.value is not None and _is_lock_factory(stmt.value)):
                    locks.add(("attr", cls.name, stmt.target.id))
        for m in methods:  # self.<x> = threading.Lock() anywhere
            self_name = m.args.args[0].arg if m.args.args else None
            if self_name is None:
                continue
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and _is_lock_factory(node.value):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == self_name
                        ):
                            locks.add(("attr", cls.name, t.attr))
        return methods, locks, cls.name

    def _scan_scope(self, ctx, fns, locks, owner, order_edges):
        diags: list[Diagnostic] = []
        events: list[tuple] = []
        for fn in fns:
            self_name = (
                fn.args.args[0].arg if owner is not None and fn.args.args
                else None
            )
            declared = {
                name
                for n in ast.walk(fn)
                if isinstance(n, ast.Global)
                for name in n.names
            }
            scan = _ScopeScan(self, ctx, self_name, locks, fn.name, events,
                              declared, owner)
            for stmt in fn.body:
                scan.visit(stmt)
            # Nested function bodies (worker loops, closures) scan as their
            # own scopes: same guarded-attribute rules, fresh lock stack.
            inner = [
                n for outer in fn.body for n in ast.walk(outer)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for g in inner:
                gscan = _ScopeScan(self, ctx, self_name, locks, fn.name,
                                   events, declared, owner)
                for stmt in g.body:
                    gscan.visit(stmt)
        guarded = {
            (k, n)
            for ev, p in events
            if ev == "mutation"
            for k, n, under, _l, _c, fname in [p]
            if under and fname not in ("__init__", "__new__")
        }
        for ev, p in events:
            if ev == "mutation":
                k, n, under, line, col, fname = p
                if (
                    not under
                    and (k, n) in guarded
                    and fname not in ("__init__", "__new__")
                ):
                    what = f"self.{n}" if k == "attr" else n
                    diags.append(
                        Diagnostic(
                            ctx.relpath, line, col, "DS201",
                            f"{what} is lock-guarded elsewhere but mutated "
                            f"here without holding the lock",
                        )
                    )
            elif ev == "blocking":
                op, lock, line, col = p
                diags.append(
                    Diagnostic(
                        ctx.relpath, line, col, "DS202",
                        f"blocking call {op!r} while holding {_lock_label(lock)}",
                    )
                )
            elif ev == "order":
                outer, inner_lock, rel, line = p
                key = (outer, inner_lock)
                rkey = (inner_lock, outer)
                if rkey in order_edges:
                    diags.append(
                        Diagnostic(
                            rel, line, 0, "DS203",
                            f"locks {_lock_label(outer)} and "
                            f"{_lock_label(inner_lock)} are acquired in both "
                            f"orders (other order at line {order_edges[rkey]});"
                            " pick one global order",
                        )
                    )
                order_edges.setdefault(key, line)
        return diags
