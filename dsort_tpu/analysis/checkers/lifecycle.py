"""Kernel/thread lifecycle checker: DMA pairing and thread discipline.

The fused ring kernel (`ops/ring_kernel.py`) lives on one invariant the
tracing checker cannot see: every `pltpu.make_async_remote_copy` that is
``.start()``ed must be drained — ``.wait()``, or BOTH ``.wait_recv()``
(the data landed) and ``.wait_send()`` (the source buffer may be reused)
— before the kernel returns or overwrites the buffers the DMA touches.
A missing wait is not a crash at trace time; it is silent corruption on
real ICI, the worst possible failure mode.  The fleet/serve/obs planes
added a second lifecycle surface: threads.  A non-daemon thread that is
never joined outlives its owner and blocks interpreter exit — the shape
that turns a clean ``dsort fleet`` Ctrl-C into a hang.

Codes
  DS901  an async remote copy is started but never waited in the same
         function: the DMA may still be in flight when the kernel
         completes
  DS902  an async remote copy drains only one direction (``wait_recv``
         without ``wait_send``, or vice versa) and never calls plain
         ``wait()``: the un-drained side races buffer reuse
  DS903  a thread-like resource leaks past its owner: a
         ``threading.Thread`` created without ``daemon=True`` and never
         ``.join()``ed, a ``threading.Timer`` never ``.cancel()``ed /
         ``.join()``ed / marked daemon, or a ``concurrent.futures``
         executor neither used as a context manager nor ``.shutdown()``
         anywhere in the module

Pairing is per enclosing function and per copy *factory*: the ring
kernels build copies through a local ``def copy(k): return
pltpu.make_async_remote_copy(...)`` — ``copy(k).start()`` pairs with
``copy(j).wait_recv()``/``copy(j).wait_send()`` on the same factory.
Direct ``make_async_remote_copy(...).start()`` chains and simple local
bindings (``c = make_async_remote_copy(...)``) resolve the same way.
Join detection for DS903 is module-wide by target name (threads are
often created in ``__init__`` and joined in ``shutdown``); timers pair
with ``.cancel()``/``.join()`` or a ``.daemon = True`` attribute set,
executors with a ``with`` block or a module-wide ``.shutdown()``.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename as _callee_basename
from dsort_tpu.analysis.astutil import own_nodes as _own_nodes
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

_DMA_FACTORY = "make_async_remote_copy"
_WAIT_ATTRS = {"wait", "wait_recv", "wait_send"}
_DRAIN_ATTRS = ("join", "cancel", "shutdown")
_EXECUTORS = ("ThreadPoolExecutor", "ProcessPoolExecutor")


class LifecycleChecker(Checker):
    name = "lifecycle"
    codes = {
        "DS901": "async remote copy started but never waited",
        "DS902": "async remote copy drains only one DMA direction",
        "DS903": "thread/timer/executor leaks past its owner",
    }
    scope = ("*.py",)

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        diags: list[Diagnostic] = []
        fns = [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn in fns:
            diags.extend(self._check_dma(ctx, fn))
        diags.extend(self._check_threads(ctx, fns))
        return diags

    # -- DS901 / DS902 -------------------------------------------------------

    @staticmethod
    def _dma_factories(fn) -> set[str]:
        """Names of local functions that return a make_async_remote_copy."""
        out = set()
        for node in fn.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Return)
                    and isinstance(sub.value, ast.Call)
                    and _callee_basename(sub.value.func) == _DMA_FACTORY
                ):
                    out.add(node.name)
                    break
        return out

    def _check_dma(self, ctx, fn) -> list[Diagnostic]:
        factories = self._dma_factories(fn)
        # Simple local bindings: c = make_async_remote_copy(...) (or a
        # factory call) — `c.start()` then pairs under the name c.
        bound: dict[str, str] = {}
        for node in _own_nodes(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                callee = _callee_basename(node.value.func)
                if callee == _DMA_FACTORY or callee in factories:
                    bound[node.targets[0].id] = callee or _DMA_FACTORY

        def copy_key(recv: ast.expr) -> str | None:
            if isinstance(recv, ast.Call):
                callee = _callee_basename(recv.func)
                if callee == _DMA_FACTORY or callee in factories:
                    return callee
            if isinstance(recv, ast.Name) and recv.id in bound:
                return recv.id
            return None

        started: dict[str, tuple[int, int]] = {}
        waits: dict[str, set[str]] = {}
        for node in _own_nodes(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            key = copy_key(node.func.value)
            if key is None:
                continue
            if node.func.attr == "start":
                started.setdefault(key, (node.lineno, node.col_offset))
            elif node.func.attr in _WAIT_ATTRS:
                waits.setdefault(key, set()).add(node.func.attr)
        diags = []
        for key, (line, col) in sorted(started.items(), key=lambda kv: kv[1]):
            got = waits.get(key, set())
            label = (
                "the remote copy" if key == _DMA_FACTORY
                else f"copies from {key!r}"
            )
            if not got:
                diags.append(
                    Diagnostic(
                        ctx.relpath, line, col, "DS901",
                        f"{label} started but never waited in "
                        f"{fn.name!r}: the DMA may still be in flight when "
                        "the kernel completes (add wait(), or wait_recv() + "
                        "wait_send())",
                    )
                )
            elif "wait" not in got and got != {"wait_recv", "wait_send"}:
                missing = sorted({"wait_recv", "wait_send"} - got)
                diags.append(
                    Diagnostic(
                        ctx.relpath, line, col, "DS902",
                        f"{label} drains {sorted(got)} but never "
                        f"{missing} in {fn.name!r}: the un-drained "
                        "direction races buffer reuse",
                    )
                )
        return diags

    # -- DS903 ---------------------------------------------------------------

    def _check_threads(self, ctx, fns) -> list[Diagnostic]:
        # Module-wide drain census: receivers of .join()/.cancel()/
        # .shutdown() by name and by attribute.
        drains: dict[str, tuple[set[str], set[str]]] = {
            a: (set(), set()) for a in _DRAIN_ATTRS
        }
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DRAIN_ATTRS
            ):
                continue
            names, attrs = drains[node.func.attr]
            recv = node.func.value
            if isinstance(recv, ast.Name):
                names.add(recv.id)
            elif isinstance(recv, ast.Attribute):
                attrs.add(recv.attr)
        # `t.daemon = True` attribute sets (the Timer idiom — Timer's
        # constructor takes no daemon kwarg).
        daemon_names: set[str] = set()
        daemon_attrs: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                continue
            recv = node.targets[0].value
            if isinstance(recv, ast.Name):
                daemon_names.add(recv.id)
            elif isinstance(recv, ast.Attribute):
                daemon_attrs.add(recv.attr)
        # Assignment targets per constructor call, and `with Executor()
        # as ex:` context expressions (scope-bounded drain by shape).
        targets: dict[int, ast.expr] = {}
        with_calls: set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                targets[id(node.value)] = node.targets[0]
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_calls.add(id(item.context_expr))

        def drained(target, *attrs_wanted: str) -> bool:
            pools = [drains[a] for a in attrs_wanted]
            if isinstance(target, ast.Name):
                return any(target.id in names for names, _ in pools)
            if isinstance(target, ast.Attribute):
                return any(target.attr in attrs for _, attrs in pools)
            # List-comprehension / loop-built resource sets: any drain
            # call in the module keeps the loose pairing honest.
            return any(names or attrs for names, attrs in pools)

        diags = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_basename(node.func)
            if callee in ("Thread", "Timer"):
                daemon = None
                for kw in node.keywords:
                    if kw.arg == "daemon":
                        daemon = kw.value
                if (
                    daemon is not None
                    and isinstance(daemon, ast.Constant)
                    and daemon.value is True
                ):
                    continue
                target = targets.get(id(node))
                if isinstance(target, ast.Name) and target.id in daemon_names:
                    continue
                if (
                    isinstance(target, ast.Attribute)
                    and target.attr in daemon_attrs
                ):
                    continue
                wanted = ("join",) if callee == "Thread" else ("join", "cancel")
                if drained(target, *wanted):
                    continue
                what = (
                    "thread is neither daemon=True nor joined"
                    if callee == "Thread"
                    else "timer is neither daemon, cancelled, nor joined"
                )
                diags.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS903",
                        f"{what} anywhere in this module: it outlives its "
                        "owner and blocks interpreter exit",
                    )
                )
            elif callee in _EXECUTORS:
                if id(node) in with_calls:
                    continue
                if drained(targets.get(id(node)), "shutdown"):
                    continue
                diags.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS903",
                        f"{callee} is neither used as a context manager nor "
                        ".shutdown() anywhere in this module: its worker "
                        "threads outlive the owner and block interpreter "
                        "exit",
                    )
                )
        return diags
