"""Spec-plane checker: protocol spec ↔ handler source, trace contracts.

Third-generation registry discipline (after DS1xx events and DS7xx
frames): the declarative protocol spec (`analysis/spec/machines.py`) and
the trace-contract registry (`analysis/spec/contracts.py`) are pure
literals, and this checker holds them and the code to each other — both
ways, by PARSING sources, never importing the linted tree.

DS10xx — spec ↔ handler cross-checks
  DS1001  malformed spec: unknown registry, event outside its registry,
          transition over an undeclared state/event, a covers_registry
          machine missing registry entries, or a spec/contracts source
          that is missing or not a pure literal
  DS1002  handler arm not declared: the dispatch function compares the
          frame type against a registry name the spec does not list as
          handled — code drifted ahead of the spec
  DS1003  declared handled frame has no handler arm — the spec promises
          a dispatch arm the code no longer has (the seeded-drift drill
          deletes one arm and must land here)
  DS1004  silent drop: in a non-terminal state, an event of the
          machine's alphabet has neither a transition nor an explicit
          ``ignorable`` entry — every dropped frame is a decision
  DS1005  obligation not discharged: the named function never calls its
          discharge function, or (``before_send``) the last send of the
          guarded frame type precedes the first discharge call — the
          persist-before-ack class of bug, statically

DS11xx — journal trace contracts
  DS1101  an ``.event(...)``/``.emit(...)`` site emits an `EVENT_TYPES`
          name that no declared contract covers and `CONTRACT_EXEMPT`
          does not exempt
  DS1102  a contract (or exempt) name does not resolve against
          `EVENT_TYPES`, a contract grammar does not compile, or a name
          is both covered and exempt
  DS1103  a hand-rolled trace-sequence literal (>= 4 contract-alphabet
          event names in one list/tuple) — the duplicated-sequence smell
          the contract engine exists to remove; use `assert_conformant`
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import (
    Checker,
    ProjectContext,
    _dict_literal_keys,
    _tuple_literal_strs,
)

#: Attribute calls that journal an event (first positional arg = name).
_EMIT_ATTRS = ("event", "emit")

#: Attribute calls that send a wire frame (DS1005 ``before_send``).
_SEND_ATTRS = ("_send", "send")


def _literal_assign(tree: ast.AST, name: str):
    """``(value, lineno)`` of the pure-literal top-level assignment to
    ``name``, or ``(None, reason)`` when absent or not a literal."""
    for node in ast.walk(tree):
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                try:
                    return ast.literal_eval(value), value.lineno
                except ValueError:
                    return None, f"{name} is not a pure literal"
    return None, f"no top-level {name} assignment"


def _dict_key_lines(tree: ast.AST, name: str) -> dict[str, int]:
    """Top-level key -> lineno for the dict literal assigned to ``name``
    (diagnostic anchors inside a literal_eval'd registry)."""
    for node in ast.walk(tree):
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id == name
                and isinstance(value, ast.Dict)
            ):
                return {
                    k.value: k.lineno
                    for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
    return {}


def _functions_named(tree: ast.AST, name: str) -> list[ast.FunctionDef]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name == name
    ]


def _sends_of(fn: ast.AST, frame_type: str) -> list[int]:
    """Line numbers of sends of ``{"type": frame_type, ...}`` in ``fn``."""
    out = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and callee_basename(node.func) in _SEND_ATTRS
        ):
            continue
        for arg in node.args:
            if not isinstance(arg, ast.Dict):
                continue
            for k, v in zip(arg.keys, arg.values):
                if (
                    isinstance(k, ast.Constant)
                    and k.value == "type"
                    and isinstance(v, ast.Constant)
                    and v.value == frame_type
                ):
                    out.append(node.lineno)
    return out


class SpecChecker(Checker):
    name = "spec"
    codes = {
        "DS1001": "malformed protocol spec (unknown registry/state/event, "
                  "or a spec source that is missing or not a pure literal)",
        "DS1002": "handler arm not declared in the protocol spec",
        "DS1003": "declared handled frame has no handler arm",
        "DS1004": "frame silently dropped in a reachable state (no "
                  "transition, no ignorable entry)",
        "DS1005": "transition obligation not discharged (missing or "
                  "mis-ordered call)",
        "DS1101": "event emission outside every declared trace contract",
        "DS1102": "trace-contract registry does not resolve (unknown "
                  "event name, non-compiling grammar, covered-and-exempt)",
        "DS1103": "hand-rolled trace-sequence literal; declare it in "
                  "TRACE_CONTRACTS and use the contract engine",
    }
    scope = ("*.py",)
    project = True  # spec ↔ source is a property of the tree

    def check_project(self, project: ProjectContext) -> list[Diagnostic]:
        cfg = project.config
        diags: list[Diagnostic] = []
        spec_rel = cfg.spec_registry_path.replace("\\", "/")
        contracts_rel = cfg.contracts_registry_path.replace("\\", "/")

        spec, spec_tree = self._load_literal(
            project, spec_rel, "PROTOCOL_SPEC", diags
        )
        contracts, contracts_tree = self._load_literal(
            project, contracts_rel, "TRACE_CONTRACTS", diags
        )
        vocabularies = self._vocabularies(project)

        if spec is not None:
            machine_lines = _dict_key_lines(spec_tree, "PROTOCOL_SPEC")
            for mname, machine in spec.items():
                line = machine_lines.get(mname, 1)
                diags.extend(
                    self._check_machine(
                        project, spec_rel, line, mname, machine, vocabularies
                    )
                )

        exempt = None
        if contracts_tree is not None:
            exempt, _ = _literal_assign(contracts_tree, "CONTRACT_EXEMPT")
        if contracts is not None:
            diags.extend(
                self._check_contracts(
                    contracts_rel, contracts_tree, contracts,
                    exempt if isinstance(exempt, tuple) else (),
                    vocabularies.get("EVENT_TYPES", set()),
                )
            )
            diags.extend(
                self._check_emissions(
                    project, contracts,
                    exempt if isinstance(exempt, tuple) else (),
                    vocabularies.get("EVENT_TYPES", set()),
                    spec_rel, contracts_rel,
                )
            )
        return diags

    # -- loading -------------------------------------------------------------

    def _load_literal(self, project, relpath, name, diags):
        src = project.source(relpath)
        if src is None:
            diags.append(
                Diagnostic(
                    relpath, 1, 0, "DS1001",
                    f"spec registry source {relpath!r} not found — the "
                    f"spec plane cannot pass vacuously",
                )
            )
            return None, None
        tree = ast.parse(src, filename=relpath)
        value, where = _literal_assign(tree, name)
        if value is None:
            diags.append(
                Diagnostic(relpath, 1, 0, "DS1001", f"{where} in {relpath}")
            )
            return None, tree
        return value, tree

    def _vocabularies(self, project) -> dict[str, set[str]]:
        """The registry vocabularies, parsed from THIS tree's sources."""
        cfg = project.config
        out: dict[str, set[str]] = {}
        src = project.source(cfg.proto_registry_path.replace("\\", "/"))
        if src is not None:
            found = _dict_literal_keys(ast.parse(src), {"FRAME_TYPES"})
            out["FRAME_TYPES"] = set(found.get("FRAME_TYPES", []))
        src = project.source(cfg.admission_registry_path.replace("\\", "/"))
        if src is not None:
            found = _tuple_literal_strs(ast.parse(src), {"ADMISSION_REASONS"})
            out["ADMISSION_REASONS"] = set(found.get("ADMISSION_REASONS", []))
        src = project.source(cfg.registry_path.replace("\\", "/"))
        if src is not None:
            found = _dict_literal_keys(ast.parse(src), {"EVENT_TYPES"})
            out["EVENT_TYPES"] = set(found.get("EVENT_TYPES", []))
        return out

    # -- DS1001..DS1005 ------------------------------------------------------

    def _check_machine(
        self, project, spec_rel, line, mname, machine, vocabularies
    ) -> list[Diagnostic]:
        diags: list[Diagnostic] = []

        def bad(code, msg, path=spec_rel, at=line):
            diags.append(Diagnostic(path, at, 0, code, f"{mname}: {msg}"))

        registry = machine.get("registry")
        vocab = vocabularies.get(registry)
        if vocab is None:
            bad("DS1001", f"unknown registry {registry!r}")
            return diags
        receives = tuple(machine.get("receives", ()))
        handled = tuple(machine.get("handled", ()))
        replies = tuple(machine.get("replies", ()))
        internal = tuple(machine.get("internal", ()))
        states = tuple(machine.get("states", ()))
        transitions = tuple(machine.get("transitions", ()))
        ignorable = dict(machine.get("ignorable", {}))
        alphabet = set(receives) | set(internal)

        for ev in receives:
            if ev not in vocab:
                bad("DS1001", f"receives {ev!r}, not in {registry}")
        for ev in handled:
            if ev not in receives:
                bad("DS1001", f"handled {ev!r} is not in receives")
        for ev in replies:
            if ev not in vocab:
                bad("DS1001", f"reply frame {ev!r}, not in {registry}")
        for ev in internal:
            if ev in vocab:
                bad("DS1001",
                    f"internal event {ev!r} collides with a {registry} name")
        if machine.get("initial") not in states:
            bad("DS1001", f"initial state {machine.get('initial')!r} "
                          f"not in states")
        if machine.get("covers_registry"):
            missing = sorted(vocab - set(receives))
            if missing:
                bad("DS1001",
                    f"covers_registry but misses {registry} entries "
                    f"{missing}")
        outgoing: dict[str, set[str]] = {s: set() for s in states}
        for row in transitions:
            if len(row) != 4:
                bad("DS1001", f"transition row {row!r} is not "
                              f"(state, event, target, guard)")
                continue
            src, ev, dst, _guard = row
            if src not in states or dst not in states:
                bad("DS1001", f"transition {src!r}-[{ev}]->{dst!r} uses an "
                              f"undeclared state")
            if ev not in alphabet:
                bad("DS1001", f"transition event {ev!r} is neither a "
                              f"received frame nor an internal event")
            outgoing.setdefault(src, set()).add(ev)
        for st, evs in ignorable.items():
            if st not in states:
                bad("DS1001", f"ignorable state {st!r} is undeclared")
            for ev in evs:
                if ev not in alphabet:
                    bad("DS1001", f"ignorable event {ev!r} in {st!r} is "
                                  f"outside the machine alphabet")

        # DS1004: in every non-terminal state, every alphabet event is
        # either transitioned or explicitly ignorable.  A state with no
        # outgoing transitions is terminal (the link/job is gone) and
        # cannot silently drop anything.
        machine_alphabet = {
            row[1] for row in transitions if len(row) == 4
        }
        for st in states:
            if not outgoing.get(st):
                continue
            for ev in sorted(machine_alphabet):
                if ev in outgoing[st]:
                    continue
                if ev in tuple(ignorable.get(st, ())):
                    continue
                bad("DS1004",
                    f"event {ev!r} in state {st!r} has no transition and "
                    f"no ignorable entry — a silent drop")

        # DS1002/DS1003: arms in the dispatch function vs handled.
        handler = machine.get("handler")
        if handler:
            hfile, hfunc = handler
            hfile = hfile.replace("\\", "/")
            src = project.source(hfile)
            if src is None:
                bad("DS1001", f"handler file {hfile!r} not found")
            else:
                htree = ast.parse(src, filename=hfile)
                fns = _functions_named(htree, hfunc)
                if not fns:
                    bad("DS1001",
                        f"handler function {hfunc!r} not found in {hfile}")
                arms: dict[str, int] = {}
                for fn in fns:
                    for node in ast.walk(fn):
                        if not isinstance(node, ast.Compare):
                            continue
                        if not any(
                            isinstance(op, ast.Eq) for op in node.ops
                        ):
                            continue
                        for cmp in [node.left, *node.comparators]:
                            if (
                                isinstance(cmp, ast.Constant)
                                and cmp.value in vocab
                            ):
                                arms.setdefault(cmp.value, cmp.lineno)
                for ev, at in sorted(arms.items()):
                    if ev not in handled:
                        bad("DS1002",
                            f"{hfunc} dispatches frame {ev!r}, which the "
                            f"spec does not declare as handled",
                            path=hfile, at=at)
                for ev in handled:
                    if ev not in arms:
                        at = fns[0].lineno if fns else line
                        bad("DS1003",
                            f"spec declares {ev!r} handled by {hfunc}, but "
                            f"the function has no arm for it",
                            path=hfile, at=at)

        # DS1005: obligations.
        for ob in machine.get("obligations", ()):
            diags.extend(self._check_obligation(project, mname, ob, line,
                                                spec_rel))
        return diags

    def _check_obligation(self, project, mname, ob, line, spec_rel):
        path = str(ob.get("file", "")).replace("\\", "/")
        func = str(ob.get("function", ""))
        must = str(ob.get("must_call", ""))
        before = ob.get("before_send")
        src = project.source(path)
        if src is None:
            return [Diagnostic(
                spec_rel, line, 0, "DS1001",
                f"{mname}: obligation file {path!r} not found",
            )]
        tree = ast.parse(src, filename=path)
        fns = _functions_named(tree, func)
        if not fns:
            return [Diagnostic(
                spec_rel, line, 0, "DS1001",
                f"{mname}: obligation function {func!r} not in {path}",
            )]
        calls = [
            node.lineno
            for fn in fns
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
            and callee_basename(node.func) == must
        ]
        if not calls:
            return [Diagnostic(
                path, fns[0].lineno, 0, "DS1005",
                f"{mname}: {func} must call {must} "
                f"({ob.get('why', 'declared obligation')}) — no call found",
            )]
        if before:
            sends = [ln for fn in fns for ln in _sends_of(fn, str(before))]
            if sends and max(sends) < min(calls):
                return [Diagnostic(
                    path, max(sends), 0, "DS1005",
                    f"{mname}: {func} sends {before!r} (line {max(sends)}) "
                    f"before discharging {must} (line {min(calls)}) — "
                    f"{ob.get('why', 'ordered obligation')}",
                )]
        return []

    # -- DS1101..DS1103 ------------------------------------------------------

    def _check_contracts(
        self, contracts_rel, contracts_tree, contracts, exempt, event_types
    ) -> list[Diagnostic]:
        # The engine code is imported from the installed analysis package,
        # but the DATA it validates is the linted tree's literal — the
        # parse-don't-import discipline applies to the tree, not to our
        # own library functions.
        from dsort_tpu.analysis.spec.contracts import (
            ContractError,
            compile_contract,
            contract_names,
        )

        diags = []
        key_lines = _dict_key_lines(contracts_tree, "TRACE_CONTRACTS")
        covered: set[str] = set()
        for cname, contract in contracts.items():
            at = key_lines.get(cname, 1)
            try:
                names = contract_names(contract)
                compile_contract(contract)
            except (ContractError, KeyError, TypeError) as e:
                diags.append(Diagnostic(
                    contracts_rel, at, 0, "DS1102",
                    f"contract {cname!r} does not compile: {e}",
                ))
                continue
            covered |= names
            for ev in sorted(names | set(contract.get("when", ()))):
                if event_types and ev not in event_types:
                    diags.append(Diagnostic(
                        contracts_rel, at, 0, "DS1102",
                        f"contract {cname!r} names {ev!r}, which is not in "
                        f"EVENT_TYPES",
                    ))
        for ev in exempt:
            if event_types and ev not in event_types:
                diags.append(Diagnostic(
                    contracts_rel, 1, 0, "DS1102",
                    f"CONTRACT_EXEMPT names {ev!r}, which is not in "
                    f"EVENT_TYPES",
                ))
            if ev in covered:
                diags.append(Diagnostic(
                    contracts_rel, 1, 0, "DS1102",
                    f"{ev!r} is both contract-covered and CONTRACT_EXEMPT",
                ))
        return diags

    def _check_emissions(
        self, project, contracts, exempt, event_types, spec_rel, contracts_rel
    ) -> list[Diagnostic]:
        from dsort_tpu.analysis.spec.contracts import (
            ContractError,
            contract_names,
        )

        covered: set[str] = set()
        for contract in contracts.values():
            try:
                covered |= contract_names(contract)
            except (ContractError, KeyError, TypeError):
                pass  # already a DS1102
        alphabet_union = covered
        ok_names = covered | set(exempt)
        diags = []
        for rel in sorted(project.relpaths):
            if not rel.endswith(".py"):
                continue
            if rel in (spec_rel, contracts_rel):
                continue  # the registries' own docstrings/literals
            src = project.source(rel)
            if src is None:
                continue
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                continue  # DS001's problem
            is_test = rel.startswith("tests/") or "/tests/" in rel
            for node in ast.walk(tree):
                # DS1101: emission sites of registered event names.
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_ATTRS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    ev = node.args[0].value
                    if (
                        not is_test
                        and ev in event_types
                        and ev not in ok_names
                    ):
                        diags.append(Diagnostic(
                            rel, node.lineno, node.col_offset, "DS1101",
                            f"event {ev!r} is emitted here but belongs to "
                            f"no declared trace contract (and is not in "
                            f"CONTRACT_EXEMPT)",
                        ))
                # DS1103: hand-rolled trace-sequence literals.
                if isinstance(node, (ast.List, ast.Tuple)):
                    names = [
                        e.value
                        for e in node.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
                    if (
                        len(names) == len(node.elts)
                        and len(names) >= 4
                        and len(set(names)) >= 2
                        and alphabet_union
                        and all(n in alphabet_union for n in names)
                    ):
                        diags.append(Diagnostic(
                            rel, node.lineno, node.col_offset, "DS1103",
                            f"hand-rolled trace sequence {names[:3] + ['...']}"
                            f" — declare the grammar in TRACE_CONTRACTS and "
                            f"assert with the contract engine",
                        ))
        return diags
