"""Compat-shim enforcement: version-drifting JAX APIs route through one door.

``utils/compat.py`` exists so that every API that has moved across JAX
versions (``shard_map``'s package, the x64 switch, Pallas compiler params)
is absorbed in ONE place.  The shim only works if nothing bypasses it — a
raw ``from jax.experimental.shard_map import shard_map`` compiles fine on
0.4.x and breaks on the next upgrade, and a scattered
``jax.config.update("jax_enable_x64", ...)`` is exactly how the x64
enablement ended up duplicated between the CLI and the worker shim.

  DS501  direct ``jax.config.update("jax_enable_x64", ...)`` outside the
         compat module (use ``utils.compat.set_x64`` / ``enable_x64``)
  DS502  raw ``shard_map`` import/use outside the compat module (import it
         from ``dsort_tpu.utils.compat``)

Reads (``jax.config.jax_enable_x64``) are fine — only mutation must be
centralized.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext

_COMPAT_SUFFIX = "utils/compat.py"


class CompatChecker(Checker):
    name = "compat"
    codes = {
        "DS501": "jax_enable_x64 toggled outside utils/compat.py",
        "DS502": "raw shard_map import outside utils/compat.py",
    }
    scope = ("*.py",)

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        if ctx.relpath.endswith(_COMPAT_SUFFIX):
            return []  # the shim itself is the one allowed call site
        out: list[Diagnostic] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                f = node.func
                # <anything>.config.update(...) AND bare config.update(...)
                # (`from jax import config`) — the bypass form.
                recv_is_config = isinstance(f, ast.Attribute) and (
                    (
                        isinstance(f.value, ast.Attribute)
                        and f.value.attr == "config"
                    )
                    or (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "config"
                    )
                )
                if (
                    recv_is_config
                    and f.attr == "update"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "jax_enable_x64"
                ):
                    out.append(
                        Diagnostic(
                            ctx.relpath, node.lineno, node.col_offset,
                            "DS501",
                            "toggle x64 via dsort_tpu.utils.compat.set_x64/"
                            "enable_x64, not jax.config.update — the shim "
                            "is the single place that tracks this API",
                        )
                    )
            elif isinstance(node, (ast.ImportFrom, ast.Import)):
                if isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    names = {a.name for a in node.names}
                    raw = (
                        mod == "jax.experimental.shard_map"
                        or (mod == "jax" and "shard_map" in names)
                        or (mod == "jax.experimental" and "shard_map" in names)
                    )
                else:  # `import jax.experimental.shard_map [as x]`
                    raw = any(
                        a.name == "jax.experimental.shard_map"
                        for a in node.names
                    )
                if raw:
                    out.append(
                        Diagnostic(
                            ctx.relpath, node.lineno, node.col_offset,
                            "DS502",
                            "import shard_map from dsort_tpu.utils.compat "
                            "(absorbs the check_vma/check_rep API split), "
                            "not from jax directly",
                        )
                    )
            elif isinstance(node, ast.Attribute):
                if (
                    node.attr == "shard_map"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"
                ):
                    out.append(
                        Diagnostic(
                            ctx.relpath, node.lineno, node.col_offset,
                            "DS502",
                            "use dsort_tpu.utils.compat.shard_map, not "
                            "jax.shard_map (absent on jax 0.4.x)",
                        )
                    )
        return out
