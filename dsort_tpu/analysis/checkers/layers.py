"""Import-layer purity checker: declared-pure modules stay backend-free.

The §12 split rests on an import-layering contract: the fleet control
plane (`fleet.proto`, `fleet.controller`, `serve.policy`, ...) must be
importable in a process that never initializes JAX, and the analysis
package itself must stay stdlib-only so linting a tree can never touch a
backend.  Before this checker the contract was enforced by ONE dynamic
subprocess test (`tests/test_fleet.py` blocks ``import jax`` and imports
the controller) — a per-module drill that does not scale to every pure
module and only fires for the modules someone remembered to drill.

This checker generalizes the contract statically: the
``[tool.dsort.lint.layers]`` pyproject table declares module patterns and
the import roots they must never reach, and the checker walks the
TRANSITIVE module-level import graph (parent ``__init__`` packages
included — importing ``a.b.c`` executes ``a`` and ``a.b`` first) from
every declared module, reading files from disk on demand so the contract
holds even when only one changed file is linted.  Function-local (lazy)
imports are deliberately out of scope: they are exactly the sanctioned
escape hatch the §12 layering uses.  ``if TYPE_CHECKING:`` blocks never
execute and are skipped.

Codes
  DS601  a declared-pure module reaches a forbidden import root at import
         time (the message carries the module chain; anchored at the
         offending import statement)
  DS602  a ``[tool.dsort.lint.layers]`` pattern matches no existing module
         — a renamed module must carry its purity contract with it, never
         silently un-declare it
"""

from __future__ import annotations

import ast
import os

from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, ProjectContext


def _is_type_checking(test: ast.expr) -> bool:
    if isinstance(test, ast.Name):
        return test.id == "TYPE_CHECKING"
    if isinstance(test, ast.Attribute):
        return test.attr == "TYPE_CHECKING"
    return False


def _iter_import_stmts(nodes):
    """Import statements that EXECUTE at module import time: top-level and
    inside top-level compound statements (try/if/with/class bodies), but
    never inside function bodies or ``if TYPE_CHECKING:`` guards."""
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If) and _is_type_checking(node.test):
            yield from _iter_import_stmts(node.orelse)
        elif isinstance(node, (ast.stmt, ast.excepthandler)):
            children = [
                c
                for c in ast.iter_child_nodes(node)
                if isinstance(c, (ast.stmt, ast.excepthandler))
            ]
            yield from _iter_import_stmts(children)


class ImportGraph:
    """Module-level import graph over the packages under ``root``.

    Modules resolve as ``root/a/b.py`` or ``root/a/b/__init__.py``; a name
    that resolves nowhere under root is an external leaf (stdlib or third
    party) — leaves are where the forbidden-root check applies, in-tree
    modules are traversed.
    """

    def __init__(self, root: str):
        self.root = root
        self._resolve_cache: dict[str, tuple[str, bool] | None] = {}
        self._imports_cache: dict[str, list[tuple[str, int]] | None] = {}

    def resolve(self, modname: str) -> tuple[str, bool] | None:
        """``(relpath, is_package)`` for an in-tree module, else None."""
        if modname in self._resolve_cache:
            return self._resolve_cache[modname]
        base = os.path.join(self.root, *modname.split("."))
        out = None
        if os.path.isfile(base + ".py"):
            out = (
                os.path.relpath(base + ".py", self.root).replace(os.sep, "/"),
                False,
            )
        elif os.path.isfile(os.path.join(base, "__init__.py")):
            out = (
                os.path.relpath(
                    os.path.join(base, "__init__.py"), self.root
                ).replace(os.sep, "/"),
                True,
            )
        self._resolve_cache[modname] = out
        return out

    def expand(self, pattern: str) -> list[str]:
        """Module names a layers pattern covers: an exact module, or every
        module under a package for a trailing ``.*``."""
        if not pattern.endswith(".*"):
            return [pattern] if self.resolve(pattern) else []
        pkg = pattern[: -len(".*")]
        resolved = self.resolve(pkg)
        if resolved is None or not resolved[1]:
            return []
        out = [pkg]
        pkg_dir = os.path.join(self.root, *pkg.split("."))
        for dirpath, dirnames, names in os.walk(pkg_dir):
            dirnames[:] = sorted(
                d for d in dirnames
                if os.path.isfile(os.path.join(dirpath, d, "__init__.py"))
            )
            relmod = os.path.relpath(dirpath, pkg_dir)
            prefix = pkg if relmod == "." else (
                pkg + "." + relmod.replace(os.sep, ".")
            )
            for name in sorted(names):
                if name == "__init__.py":
                    if prefix != pkg:
                        out.append(prefix)
                elif name.endswith(".py"):
                    out.append(f"{prefix}.{name[:-3]}")
        return sorted(out)

    def module_imports(self, modname: str) -> list[tuple[str, int]] | None:
        """``(imported_dotted_name, line)`` pairs for one module's
        import-time imports (relative imports resolved; ``from X import
        n`` contributes ``X`` plus ``X.n`` when ``X.n`` is a module)."""
        if modname in self._imports_cache:
            return self._imports_cache[modname]
        resolved = self.resolve(modname)
        out: list[tuple[str, int]] | None = None
        if resolved is not None:
            relpath, is_pkg = resolved
            try:
                with open(
                    os.path.join(self.root, relpath.replace("/", os.sep)),
                    encoding="utf-8",
                ) as f:
                    tree = ast.parse(f.read(), filename=relpath)
            except (OSError, SyntaxError):
                tree = None
            if tree is not None:
                out = []
                for stmt in _iter_import_stmts(tree.body):
                    out.extend(self._stmt_targets(stmt, modname, is_pkg))
        self._imports_cache[modname] = out
        return out

    def _stmt_targets(self, stmt, modname: str, is_pkg: bool):
        line = stmt.lineno
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                yield alias.name, line
            return
        # ImportFrom: resolve the (possibly relative) base module.
        parts = modname.split(".")
        if stmt.level:
            # level 1 = the containing package (the module itself, for a
            # package __init__); each further level strips one package.
            anchor = parts if is_pkg else parts[:-1]
            anchor = anchor[: len(anchor) - (stmt.level - 1)]
            base = ".".join(anchor)
            if stmt.module:
                base = f"{base}.{stmt.module}" if base else stmt.module
        else:
            base = stmt.module or ""
        if base:
            yield base, line
        for alias in stmt.names:
            if alias.name == "*":
                continue
            cand = f"{base}.{alias.name}" if base else alias.name
            # `from a.b import c`: c may itself be a submodule.
            if self.resolve(cand) is not None:
                yield cand, line


def _forbidden_root(name: str, forbidden: tuple[str, ...]) -> str | None:
    for f in forbidden:
        if name == f or name.startswith(f + "."):
            return f
    return None


class LayersChecker(Checker):
    name = "layers"
    codes = {
        "DS601": "declared-pure module reaches a forbidden import root "
                 "at import time",
        "DS602": "[tool.dsort.lint.layers] names a module that does not "
                 "exist",
    }
    scope = ()  # project-wide: the engine calls check_project once per run
    project = True

    def check_project(self, project: ProjectContext) -> list[Diagnostic]:
        config = project.config
        if not config.layers:
            return []
        graph = ImportGraph(config.root)
        diags: list[Diagnostic] = []
        for pattern in sorted(config.layers):
            forbidden = tuple(config.layers[pattern])
            mods = graph.expand(pattern)
            if not mods:
                diags.append(
                    Diagnostic(
                        "pyproject.toml", 1, 0, "DS602",
                        f"[tool.dsort.lint.layers] pattern {pattern!r} "
                        "matches no existing module — a renamed module must "
                        "carry its purity contract, not silently shed it",
                    )
                )
                continue
            for mod in mods:
                diags.extend(
                    self._check_module(graph, project, mod, pattern, forbidden)
                )
        return diags

    def _check_module(
        self,
        graph: ImportGraph,
        project: ProjectContext,
        mod: str,
        pattern: str,
        forbidden: tuple[str, ...],
    ) -> list[Diagnostic]:
        # Importing a.b.c executes a and a.b first: seed the closure with
        # the module AND its parent packages.
        parts = mod.split(".")
        seeds = [".".join(parts[: i + 1]) for i in range(len(parts))]
        via: dict[str, str | None] = {}
        queue: list[str] = []
        for s in seeds:
            if graph.resolve(s) is not None and s not in via:
                via[s] = None if s == mod else mod
                queue.append(s)
        findings: list[tuple[str, str, int, str, str]] = []
        closure_files: set[str] = set()
        while queue:
            cur = queue.pop(0)
            resolved = graph.resolve(cur)
            if resolved is None:
                continue
            closure_files.add(resolved[0])
            imports = graph.module_imports(cur)
            if imports is None:
                continue
            for name, line in imports:
                root_hit = _forbidden_root(name, forbidden)
                if root_hit is not None:
                    findings.append((cur, name, line, resolved[0], root_hit))
                    continue
                # Traverse in-tree targets (and their parent packages).
                nparts = name.split(".")
                for i in range(len(nparts)):
                    sub = ".".join(nparts[: i + 1])
                    if graph.resolve(sub) is not None and sub not in via:
                        via[sub] = cur
                        queue.append(sub)
        # The contract is checked when the lint run touches any file of the
        # closure (the whole-tree gate and `--changed` both qualify); a
        # fixture run far from the declared modules stays silent.
        if not (closure_files & project.relpaths):
            return []
        diags = []
        for cur, name, line, relpath, root_hit in findings:
            chain: list[str] = [cur]
            while via.get(chain[-1]):
                chain.append(via[chain[-1]])
            chain = list(reversed(chain))
            hop = " -> ".join(chain + [name])
            diags.append(
                Diagnostic(
                    relpath, line, 0, "DS601",
                    f"layer {pattern!r} forbids importing {root_hit!r}, but "
                    f"{mod} reaches {name!r} at import time ({hop}); move "
                    "the import into the function that needs it or re-layer "
                    "the module",
                )
            )
        return diags
