"""DS12xx: the collective-schedule verifier.

Proves, per module that declares an ``SPMD_CONTRACT`` (and REQUIRES the
declaration from the modules `spmd.registry` lists):

- DS1201 — every ``ppermute`` table is the declared permutation of the
  mesh axis: each closed-form builder is evaluated over the bounded
  (P, step) grid and checked for validity (in-range, no duplicate source
  or destination, full builders cover the axis) AND conformance to the
  contract's expected destination form — an inverted shift is still a
  bijection, so validity alone would not catch it.  Every ``ppermute``
  call site must trace its table to a declared builder.
- DS1202 — no collective under a trace-divergent branch: a collective
  inside an ``if`` whose test derives from ``axis_index`` (or a
  ``lax.cond``/``switch`` on such a predicate whose branch issues one)
  deadlocks the mesh when devices disagree.  Host-plane modules
  (``plane: "host"``) must issue no collectives at all.
- DS1203 — every axis name a collective uses resolves to a constructed
  mesh axis: either the contract's declared axis parameter (bound by the
  caller's ``shard_map``) or a string literal in the registry's
  ``MESH_AXES`` vocabulary, which itself must be defined by the mesh
  construction sources.
- DS1204 — every started remote DMA's (slot, step) write region is
  disjoint from all others in the same kernel: the ``pl.ds(offs[k],
  caps[k])`` destinations are evaluated from the kernel's own offset
  arithmetic over sample caps ladders and checked pairwise.

DS1200 is the loud-failure channel: a missing/malformed contract, an
undeclared required minimum, or a closed form that left the statically
evaluable subset can never pass vacuously.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext
from dsort_tpu.analysis.spmd.contract import (
    ContractError,
    extract_contract,
    iter_domain,
    load_spmd_registry,
)
from dsort_tpu.analysis.spmd.symeval import (
    EvalError,
    Evaluator,
    extract_functions,
)

#: Collective operations the verifier tracks (mesh-blocking: every device
#: must issue the same sequence).
COLLECTIVES = {
    "ppermute",
    "pshuffle",
    "all_gather",
    "all_to_all",
    "psum",
    "psum_scatter",
    "pmax",
    "pmin",
    "make_async_remote_copy",
}

#: Names whose results vary per device under one trace (taint seeds).
_DEVICE_VARYING = {"axis_index", "program_id"}

#: Remote-DMA regions with no static extent act as whole-buffer writes.
_WHOLE = (0, 1 << 62)


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FnScan:
    """One function's SPMD-relevant surface, def-boundary scoped."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.sites: list[tuple[ast.Call, list[ast.expr]]] = []
        self.conds: list[tuple[ast.Call, list[ast.expr]]] = []
        self.assign_calls: dict[str, list[str]] = {}
        self.local_defs: dict[str, ast.FunctionDef] = {}
        self._stmts(fn.body, [])
        self.tainted = self._taint()

    def _stmts(self, stmts, tests: list[ast.expr]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.local_defs[st.name] = st
                continue
            if isinstance(st, ast.If):
                self._exprs(st.test, tests)
                inner = tests + [st.test]
                self._stmts(st.body, inner)
                self._stmts(st.orelse, inner)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._exprs(st.iter, tests)
                self._stmts(st.body, tests)
                self._stmts(st.orelse, tests)
            elif isinstance(st, ast.While):
                self._exprs(st.test, tests)
                self._stmts(st.body, tests)
                self._stmts(st.orelse, tests)
            elif isinstance(st, ast.Try):
                self._stmts(st.body, tests)
                for h in st.handlers:
                    self._stmts(h.body, tests)
                self._stmts(st.orelse, tests)
                self._stmts(st.finalbody, tests)
            elif isinstance(st, ast.With):
                for item in st.items:
                    self._exprs(item.context_expr, tests)
                self._stmts(st.body, tests)
            else:
                self._exprs(st, tests)

    def _exprs(self, node: ast.AST, tests: list[ast.expr]) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                base = callee_basename(n.func)
                if base in COLLECTIVES:
                    self.sites.append((n, tests))
                elif base in ("cond", "switch"):
                    self.conds.append((n, tests))
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                base = callee_basename(n.value.func)
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.assign_calls.setdefault(t.id, []).append(base)

    def _taint(self) -> set[str]:
        assigns: list[tuple[set[str], ast.expr]] = []
        for st in ast.walk(self.fn):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if st is not self.fn:
                    continue
            targets: list[ast.expr] = []
            value = None
            if isinstance(st, ast.Assign):
                targets, value = st.targets, st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets, value = [st.target], st.value
            elif isinstance(st, ast.AugAssign):
                targets, value = [st.target], st.value
            if value is None:
                continue
            names = set()
            for t in targets:
                names |= _names_in(t)
            assigns.append((names, value))
        tainted: set[str] = set()
        for names, value in assigns:
            for n in ast.walk(value):
                if (
                    isinstance(n, ast.Call)
                    and callee_basename(n.func) in _DEVICE_VARYING
                ):
                    tainted |= names
        for _ in range(len(assigns) + 1):
            grew = False
            for names, value in assigns:
                if names <= tainted:
                    continue
                if _names_in(value) & tainted:
                    tainted |= names
                    grew = True
            if not grew:
                break
        return tainted


class SpmdChecker(Checker):
    name = "spmd"
    codes = {
        "DS1200": (
            "SPMD contract missing, malformed, or a declared closed form "
            "is not statically evaluable"
        ),
        "DS1201": (
            "ppermute table is not the declared permutation of the mesh "
            "axis"
        ),
        "DS1202": (
            "collective issued under a trace-divergent branch (or from a "
            "host-only module)"
        ),
        "DS1203": (
            "collective axis name does not resolve to a constructed mesh "
            "axis"
        ),
        "DS1204": (
            "remote DMA write regions in one kernel are not provably "
            "disjoint"
        ),
    }
    scope = ("dsort_tpu/*",)

    def __init__(self, scope=None):
        super().__init__(scope)
        self._registry_memo: dict[str, tuple] = {}
        self._axis_vocab_memo: dict[str, tuple] = {}

    # -- shared plumbing ----------------------------------------------------

    def _registry(self, ctx: FileContext):
        """(registry dict | None, error Diagnostic | None), memoized."""
        rel = ctx.config.spmd_registry_path.replace("\\", "/")
        path = ctx.config.abspath(ctx.config.spmd_registry_path)
        if path not in self._registry_memo:
            try:
                self._registry_memo[path] = (load_spmd_registry(path), None)
            except ContractError as e:
                self._registry_memo[path] = (
                    None,
                    Diagnostic(rel, e.lineno, 0, "DS1200", str(e)),
                )
        return self._registry_memo[path]

    def _axis_vocab(self, ctx: FileContext, registry: dict) -> set[str]:
        """Axis-name strings the mesh construction sources define."""
        key = ctx.config.root
        if key not in self._axis_vocab_memo:
            vocab: set[str] = set()
            for rel in registry["MESH_AXIS_SOURCES"]:
                path = ctx.config.abspath(rel)
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=path)
                except (OSError, SyntaxError):
                    continue
                for node in ast.walk(tree):
                    targets: list[ast.expr] = []
                    value = None
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif (
                        isinstance(node, ast.AnnAssign)
                        and node.value is not None
                    ):
                        targets, value = [node.target], node.value
                    if not (
                        isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                    ):
                        continue
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id.endswith(
                            "axis_name"
                        ):
                            vocab.add(value.value)
            self._axis_vocab_memo[key] = tuple(sorted(vocab))
        return set(self._axis_vocab_memo[key])

    # -- the pass -----------------------------------------------------------

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        if ctx.tree is None:
            return []
        out: list[Diagnostic] = []
        registry, reg_err = self._registry(ctx)
        try:
            contract, line = extract_contract(ctx.tree)
        except ContractError as e:
            return [Diagnostic(ctx.relpath, e.lineno, 0, "DS1200", str(e))]
        required = (
            ctx.relpath in registry["SPMD_REQUIRED"] if registry else False
        )
        if contract is None and not required:
            return []
        if reg_err is not None:
            return [reg_err]
        if contract is None:
            return [
                Diagnostic(
                    ctx.relpath, 1, 0, "DS1200",
                    "module is required to declare an SPMD_CONTRACT "
                    "(analysis/spmd/registry.py SPMD_REQUIRED) but does not",
                )
            ]
        bad_keys = sorted(set(contract) - {
            "plane", "axis_param", "perms", "layouts", "caps", "stores",
            "consts",
        })
        if bad_keys:
            out.append(
                Diagnostic(
                    ctx.relpath, line, 0, "DS1200",
                    f"SPMD_CONTRACT has unknown keys {bad_keys}",
                )
            )
        plane = contract.get("plane")
        if plane not in ("device", "host"):
            out.append(
                Diagnostic(
                    ctx.relpath, line, 0, "DS1200",
                    "SPMD_CONTRACT must declare plane: 'device' or 'host'",
                )
            )
            return out
        scans = [
            _FnScan(fn)
            for fn in ast.walk(ctx.tree)
            if isinstance(fn, ast.FunctionDef)
        ]
        if plane == "host":
            for scan in scans:
                for call, _tests in scan.sites:
                    out.append(
                        Diagnostic(
                            ctx.relpath, call.lineno, call.col_offset,
                            "DS1202",
                            f"collective {callee_basename(call.func)!r} "
                            "issued from a module declared host-only "
                            "(plane: 'host')",
                        )
                    )
            return out
        functions = extract_functions(ctx.tree)
        perms = contract.get("perms", {})
        out.extend(
            self._check_required_minima(ctx, registry, line, perms, contract)
        )
        out.extend(self._check_perm_builders(ctx, registry, perms, functions))
        axis_param = contract.get("axis_param", "axis")
        declared = set(perms)
        for scan in scans:
            out.extend(
                self._check_sites(
                    ctx, registry, scan, axis_param, declared
                )
            )
        out.extend(
            self._check_layouts(
                ctx, registry, contract.get("layouts", {}), functions, line
            )
        )
        return out

    def _check_required_minima(
        self, ctx, registry, line, perms, contract
    ) -> list[Diagnostic]:
        out = []
        for section, table in (
            ("perms", registry["SPMD_REQUIRED_PERMS"]),
            ("layouts", registry["SPMD_REQUIRED_LAYOUTS"]),
        ):
            needed = table.get(ctx.relpath, ())
            have = contract.get(section, {})
            for name in needed:
                if name not in have:
                    out.append(
                        Diagnostic(
                            ctx.relpath, line, 0, "DS1200",
                            f"SPMD_CONTRACT must declare {section}[{name!r}] "
                            "(analysis/spmd/registry.py minimum)",
                        )
                    )
        return out

    # -- DS1201: closed-form builders ---------------------------------------

    def _check_perm_builders(
        self, ctx, registry, perms, functions
    ) -> list[Diagnostic]:
        out = []
        for name, spec in sorted(perms.items()):
            fn = functions.get(name)
            if fn is None:
                out.append(
                    Diagnostic(
                        ctx.relpath, 1, 0, "DS1200",
                        f"declared perm builder {name!r} not found at "
                        "module top level",
                    )
                )
                continue
            if not isinstance(spec, dict):
                out.append(
                    Diagnostic(
                        ctx.relpath, fn.lineno, 0, "DS1200",
                        f"perms[{name!r}] must be a dict",
                    )
                )
                continue
            diag = self._verify_builder(ctx, registry, name, spec, fn)
            if diag is not None:
                out.append(diag)
        return out

    def _verify_builder(
        self, ctx, registry, name, spec, fn
    ) -> Diagnostic | None:
        ev = Evaluator(extract_functions(ctx.tree))
        args = spec.get("args")
        domain = spec.get("domain")
        kind = spec.get("kind")
        axis_size = spec.get("axis_size")
        if (
            not isinstance(args, (list, tuple))
            or not isinstance(domain, dict)
            or kind not in ("full", "partial")
            or not isinstance(axis_size, str)
        ):
            return Diagnostic(
                ctx.relpath, fn.lineno, 0, "DS1200",
                f"perms[{name!r}] needs args/domain/kind/axis_size",
            )
        try:
            for env in iter_domain(domain, registry, ev):
                p = ev.eval_str(axis_size, env)
                pairs = ev.call(name, [env[a] for a in args])
                bad = self._perm_violation(pairs, p, kind)
                if bad is None and "dst" in spec:
                    for src, dst in pairs:
                        want = ev.eval_str(spec["dst"], {**env, "i": src})
                        if dst != want:
                            bad = (
                                f"destination of source {src} is {dst}, "
                                f"declared form gives {want}"
                            )
                            break
                if bad is None and "pairs" in spec:
                    want = ev.eval_str(spec["pairs"], env)
                    if sorted(tuple(x) for x in pairs) != sorted(
                        tuple(x) for x in want
                    ):
                        bad = "pair set differs from the declared closed form"
                if bad is not None:
                    at = ", ".join(f"{a}={env[a]}" for a in args)
                    return Diagnostic(
                        ctx.relpath, fn.lineno, 0, "DS1201",
                        f"{name}({at}): {bad}",
                    )
        except EvalError as e:
            return Diagnostic(
                ctx.relpath, fn.lineno, 0, "DS1200",
                f"perm builder {name!r} is not statically evaluable: {e}",
            )
        return None

    @staticmethod
    def _perm_violation(pairs, p, kind) -> str | None:
        if not isinstance(pairs, (list, tuple)) or not all(
            isinstance(x, (list, tuple))
            and len(x) == 2
            and all(isinstance(v, int) and not isinstance(v, bool) for v in x)
            for x in pairs
        ):
            return "builder did not return a list of (src, dst) int pairs"
        srcs = [s for s, _ in pairs]
        dsts = [d for _, d in pairs]
        for v in srcs + dsts:
            if not 0 <= v < p:
                return f"index {v} is outside the axis [0, {p})"
        if len(set(srcs)) != len(srcs):
            return "duplicate source (a device sends twice)"
        if len(set(dsts)) != len(dsts):
            return "duplicate destination (two devices write one slot)"
        if kind == "full" and set(srcs) != set(range(p)):
            return "missing source: table does not cover the axis"
        return None

    # -- DS1201/DS1202/DS1203: call sites ------------------------------------

    def _check_sites(
        self, ctx, registry, scan, axis_param, declared
    ) -> list[Diagnostic]:
        out = []
        for call, tests in scan.sites:
            base = callee_basename(call.func)
            if base == "ppermute":
                out.extend(self._check_perm_arg(ctx, scan, call, declared))
            if base != "make_async_remote_copy":
                out.extend(
                    self._check_axis_arg(
                        ctx, registry, call, axis_param
                    )
                )
            for test in tests:
                if _names_in(test) & scan.tainted:
                    out.append(
                        Diagnostic(
                            ctx.relpath, call.lineno, call.col_offset,
                            "DS1202",
                            f"collective {base!r} under a branch on "
                            "device-varying state "
                            f"({scan.fn.name}): divergent participation "
                            "deadlocks the mesh",
                        )
                    )
                    break
        for call, _tests in scan.conds:
            if not call.args:
                continue
            if not (_names_in(call.args[0]) & scan.tainted):
                continue
            for branch in call.args[1:]:
                body = None
                if (
                    isinstance(branch, ast.Name)
                    and branch.id in scan.local_defs
                ):
                    body = scan.local_defs[branch.id]
                elif isinstance(branch, ast.Lambda):
                    body = branch
                if body is None:
                    continue
                if any(
                    isinstance(n, ast.Call)
                    and callee_basename(n.func) in COLLECTIVES
                    for n in ast.walk(body)
                ):
                    out.append(
                        Diagnostic(
                            ctx.relpath, call.lineno, call.col_offset,
                            "DS1202",
                            "collective inside a cond/switch branch on "
                            f"device-varying state ({scan.fn.name}): "
                            "divergent participation deadlocks the mesh",
                        )
                    )
                    break
        return out

    def _check_perm_arg(self, ctx, scan, call, declared) -> list[Diagnostic]:
        perm = None
        if len(call.args) >= 3:
            perm = call.args[2]
        else:
            for kw in call.keywords:
                if kw.arg == "perm":
                    perm = kw.value
        if perm is None:
            return []
        if isinstance(perm, ast.Call) and callee_basename(
            perm.func
        ) in declared:
            return []
        if isinstance(perm, ast.Name):
            builders = scan.assign_calls.get(perm.id, [])
            if builders and all(b in declared for b in builders):
                return []
        return [
            Diagnostic(
                ctx.relpath, call.lineno, call.col_offset, "DS1201",
                "ppermute table does not trace to a declared closed-form "
                f"builder ({scan.fn.name}); declare it in "
                "SPMD_CONTRACT['perms'] so it is verified",
            )
        ]

    def _check_axis_arg(
        self, ctx, registry, call, axis_param
    ) -> list[Diagnostic]:
        axis = None
        if len(call.args) >= 2:
            axis = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis = kw.value
        if axis is None:
            return []
        if isinstance(axis, ast.Name) and axis.id == axis_param:
            return []
        if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
            if axis.value in registry["MESH_AXES"]:
                vocab = self._axis_vocab(ctx, registry)
                if axis.value in vocab:
                    return []
                rel = ctx.config.spmd_registry_path.replace("\\", "/")
                return [
                    Diagnostic(
                        rel, 1, 0, "DS1203",
                        f"MESH_AXES declares {axis.value!r} but no mesh "
                        "construction source defines that axis name",
                    )
                ]
            return [
                Diagnostic(
                    ctx.relpath, call.lineno, call.col_offset, "DS1203",
                    f"axis name {axis.value!r} is not in the constructed "
                    "mesh vocabulary (analysis/spmd/registry.py MESH_AXES)",
                )
            ]
        return [
            Diagnostic(
                ctx.relpath, call.lineno, call.col_offset, "DS1203",
                "collective axis is neither the declared axis parameter "
                f"({axis_param!r}) nor a literal mesh axis name",
            )
        ]

    # -- DS1204: remote-DMA slot layout --------------------------------------

    def _check_layouts(
        self, ctx, registry, layouts, functions, cline
    ) -> list[Diagnostic]:
        out = []
        if not isinstance(layouts, dict):
            return [
                Diagnostic(
                    ctx.relpath, cline, 0, "DS1200",
                    "SPMD_CONTRACT['layouts'] must be a dict",
                )
            ]
        for name in sorted(layouts):
            fn = functions.get(name)
            if fn is None:
                out.append(
                    Diagnostic(
                        ctx.relpath, 1, 0, "DS1200",
                        f"declared kernel {name!r} not found at module "
                        "top level",
                    )
                )
                continue
            out.extend(self._verify_layout(ctx, registry, fn))
        return out

    def _verify_layout(self, ctx, registry, fn) -> list[Diagnostic]:
        sites = self._dma_sites(fn)
        if sites is None:
            return [
                Diagnostic(
                    ctx.relpath, fn.lineno, 0, "DS1200",
                    f"kernel {fn.name!r}: a remote DMA destination is not "
                    "of the provable NAME.at[pl.ds(start, size)] shape",
                )
            ]
        if not sites:
            return [
                Diagnostic(
                    ctx.relpath, fn.lineno, 0, "DS1200",
                    f"declared kernel {fn.name!r} starts no remote DMA "
                    "(stale layouts declaration?)",
                )
            ]
        ev = Evaluator(extract_functions(ctx.tree))
        for p in registry["MESH_SIZES"]:
            caps = tuple(8 * (1 + (i * 3) % 4) for i in range(p))
            env = {"num_workers": p, "caps": caps}
            for st in fn.body:
                if isinstance(st, ast.Assign) and len(st.targets) == 1:
                    t = st.targets[0]
                    if isinstance(t, ast.Name):
                        try:
                            env[t.id] = ev.eval_expr(st.value, dict(env))
                        except EvalError:
                            pass
            regions: dict[str, list] = {}
            for buf, start, size, param, line, col in sites:
                steps = range(p) if param is not None else range(1)
                for k in steps:
                    kenv = dict(env)
                    if param is not None:
                        kenv[param] = k
                    if start is None:
                        lo, ln = _WHOLE
                    else:
                        try:
                            lo = ev.eval_expr(start, kenv)
                            ln = ev.eval_expr(size, kenv)
                        except EvalError as e:
                            return [
                                Diagnostic(
                                    ctx.relpath, line, col, "DS1200",
                                    f"kernel {fn.name!r}: DMA region not "
                                    f"statically evaluable at P={p}: {e}",
                                )
                            ]
                    if not (
                        isinstance(lo, int) and isinstance(ln, int)
                    ) or lo < 0 or ln < 0:
                        return [
                            Diagnostic(
                                ctx.relpath, line, col, "DS1204",
                                f"kernel {fn.name!r}: DMA region "
                                f"[{lo}, +{ln}) at step {k} (P={p}) is "
                                "negative or non-integer",
                            )
                        ]
                    regions.setdefault(buf, []).append((lo, ln, k, line, col))
            for buf, spans in regions.items():
                spans = [s for s in spans if s[1] > 0]
                spans.sort()
                for a, b in zip(spans, spans[1:]):
                    if b[0] < a[0] + a[1]:
                        return [
                            Diagnostic(
                                ctx.relpath, b[3], b[4], "DS1204",
                                f"kernel {fn.name!r}: remote DMA writes "
                                f"into {buf!r} overlap at P={p}: step "
                                f"{a[2]} region [{a[0]}, {a[0] + a[1]}) vs "
                                f"step {b[2]} region [{b[0]}, "
                                f"{b[0] + b[1]})",
                            )
                        ]
        return []

    @staticmethod
    def _dma_sites(fn):
        """[(buffer, start expr|None, size expr|None, index param|None,
        line, col)] for every remote DMA under ``fn``; None when any
        destination has an unprovable shape."""
        sites = []

        def enclosing_param(target):
            param = None
            stack = [(fn, None)]
            while stack:
                node, p = stack.pop()
                for child in ast.iter_child_nodes(node):
                    cp = p
                    if isinstance(child, ast.FunctionDef):
                        if len(child.args.args) == 1:
                            cp = child.args.args[0].arg
                        elif child.args.args:
                            cp = "<multi>"
                    if child is target:
                        return cp
                    stack.append((child, cp))
            return param

        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and callee_basename(node.func) == "make_async_remote_copy"
            ):
                continue
            dst = None
            for kw in node.keywords:
                if kw.arg == "dst_ref":
                    dst = kw.value
            if dst is None and len(node.args) >= 2:
                dst = node.args[1]
            param = enclosing_param(node)
            if param == "<multi>":
                return None
            if isinstance(dst, ast.Name):
                sites.append(
                    (dst.id, None, None, param, node.lineno, node.col_offset)
                )
                continue
            if (
                isinstance(dst, ast.Subscript)
                and isinstance(dst.value, ast.Attribute)
                and dst.value.attr == "at"
                and isinstance(dst.slice, ast.Call)
                and callee_basename(dst.slice.func) == "ds"
                and len(dst.slice.args) == 2
            ):
                sites.append(
                    (
                        ast.unparse(dst.value.value),
                        dst.slice.args[0],
                        dst.slice.args[1],
                        param,
                        node.lineno,
                        node.col_offset,
                    )
                )
                continue
            return None
        return sites
