"""DS13xx: the capacity/layout abstract interpreter.

Verifies the cap-ladder arithmetic the no-retry doctrine leans on
("ring-path overflow is an invariant violation" — the buffers are sized
from the measured histogram, so the quantizers must COVER every measured
max).  Each module's ``SPMD_CONTRACT`` declares its capacity functions with
the properties they must satisfy; the checker evaluates the functions —
from the linted source, never imported — over the bounded grids in
`spmd.registry` and checks every property at every point:

- DS1301 cap-not-covering: ``quantize(m) >= m`` over the declared domain
  (``_quantize_cap`` for measured maxes up to ``n_local``, ``pad_rung``
  for job sizes, the ladder reaching its ``hi``).
- DS1302 overlapping-slot-layout: slot offsets must be the monotone
  non-overlapping partial sums of the caps (``_step_offsets``), and every
  declared receive-canvas store must keep its re-pack hop (the hier DCN
  leg's ``_pad_run(..., agg_total, ...)`` — deleting it stores a
  ``leg_caps[s]``-sized buffer into an ``agg_total`` row).
- DS1303 unaligned/degenerate size: no clamp chain may produce a zero,
  negative, or non-8-aligned buffer (``ring_step_quantum`` stays on the
  8 grid, caps stay on the quantum ladder, ``WAVE_MIN/MAX_ELEMS`` and the
  redundancy clamp stay positive and ordered).

DS1300 is the loud-failure channel (malformed/missing declarations, a cap
function outside the evaluable subset) — the same no-vacuous-pass doctrine
as DS1200.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.astutil import callee_basename
from dsort_tpu.analysis.core import Diagnostic
from dsort_tpu.analysis.engine import Checker, FileContext
from dsort_tpu.analysis.spmd.contract import (
    ContractError,
    extract_contract,
    iter_domain,
    load_spmd_registry,
    module_const_env,
)
from dsort_tpu.analysis.spmd.symeval import (
    EvalError,
    Evaluator,
    extract_functions,
)


class CapsChecker(Checker):
    name = "caps"
    codes = {
        "DS1300": (
            "capacity contract missing, malformed, or a declared cap "
            "function is not statically evaluable"
        ),
        "DS1301": "capacity quantization does not cover the measured demand",
        "DS1302": (
            "slot layout overlaps, or a declared receive-canvas re-pack "
            "hop is missing"
        ),
        "DS1303": (
            "cap/clamp chain can produce a zero, negative, or unaligned "
            "size"
        ),
    }
    scope = ("dsort_tpu/*",)

    def __init__(self, scope=None):
        super().__init__(scope)
        self._registry_memo: dict[str, tuple] = {}

    def _registry(self, ctx: FileContext):
        rel = ctx.config.spmd_registry_path.replace("\\", "/")
        path = ctx.config.abspath(ctx.config.spmd_registry_path)
        if path not in self._registry_memo:
            try:
                self._registry_memo[path] = (load_spmd_registry(path), None)
            except ContractError as e:
                self._registry_memo[path] = (
                    None,
                    Diagnostic(rel, e.lineno, 0, "DS1300", str(e)),
                )
        return self._registry_memo[path]

    def check(self, ctx: FileContext) -> list[Diagnostic]:
        if ctx.tree is None:
            return []
        registry, reg_err = self._registry(ctx)
        try:
            contract, line = extract_contract(ctx.tree)
        except ContractError:
            # The spmd checker owns the malformed-contract finding; a second
            # copy here would double-report one defect.
            return []
        caps_required = (
            registry is not None
            and (
                ctx.relpath in registry["SPMD_REQUIRED_CAPS"]
                or ctx.relpath in registry["SPMD_REQUIRED_STORES"]
                or ctx.relpath in registry["SPMD_REQUIRED_CONSTS"]
            )
        )
        if contract is None and not caps_required:
            return []
        if reg_err is not None:
            return [reg_err]
        contract = contract or {}
        out: list[Diagnostic] = []
        functions = extract_functions(ctx.tree)
        for section, table in (
            ("caps", registry["SPMD_REQUIRED_CAPS"]),
            ("stores", registry["SPMD_REQUIRED_STORES"]),
            ("consts", registry["SPMD_REQUIRED_CONSTS"]),
        ):
            have = contract.get(section, {})
            for name in table.get(ctx.relpath, ()):
                if name not in have:
                    out.append(
                        Diagnostic(
                            ctx.relpath, max(line, 1), 0, "DS1300",
                            f"SPMD_CONTRACT must declare {section}[{name!r}] "
                            "(analysis/spmd/registry.py minimum)",
                        )
                    )
        out.extend(
            self._check_caps(
                ctx, registry, contract.get("caps", {}), functions
            )
        )
        out.extend(self._check_consts(ctx, contract.get("consts", {})))
        out.extend(self._check_stores(ctx, contract.get("stores", {})))
        return out

    # -- declared cap functions ---------------------------------------------

    def _check_caps(self, ctx, registry, caps, functions) -> list[Diagnostic]:
        out: list[Diagnostic] = []
        if not isinstance(caps, dict):
            return [
                Diagnostic(
                    ctx.relpath, 1, 0, "DS1300",
                    "SPMD_CONTRACT['caps'] must be a dict",
                )
            ]
        for name, spec in sorted(caps.items()):
            fn = functions.get(name)
            if fn is None:
                out.append(
                    Diagnostic(
                        ctx.relpath, 1, 0, "DS1300",
                        f"declared cap function {name!r} not found at "
                        "module top level",
                    )
                )
                continue
            args = spec.get("args") if isinstance(spec, dict) else None
            domain = spec.get("domain") if isinstance(spec, dict) else None
            require = spec.get("require") if isinstance(spec, dict) else None
            if (
                not isinstance(args, (list, tuple))
                or not isinstance(domain, dict)
                or not isinstance(require, (list, tuple))
                or not all(
                    isinstance(r, (list, tuple))
                    and len(r) == 2
                    and r[0] in self.codes
                    for r in require
                )
            ):
                out.append(
                    Diagnostic(
                        ctx.relpath, fn.lineno, 0, "DS1300",
                        f"caps[{name!r}] needs args/domain and (code, "
                        "property) require pairs",
                    )
                )
                continue
            ev = Evaluator(functions)
            failed: dict[int, tuple] = {}
            try:
                for env in iter_domain(domain, registry, ev):
                    result = ev.call(name, [env[a] for a in args])
                    scope = {**env, "out": result}
                    for i, (_code, prop) in enumerate(require):
                        if i in failed:
                            continue
                        if not ev.eval_str(prop, scope):
                            failed[i] = (dict(env), result)
                    if len(failed) == len(require):
                        break
            except EvalError as e:
                out.append(
                    Diagnostic(
                        ctx.relpath, fn.lineno, 0, "DS1300",
                        f"cap function {name!r} is not statically "
                        f"evaluable: {e}",
                    )
                )
                continue
            for i, (env, result) in sorted(failed.items()):
                code, prop = require[i]
                at = ", ".join(f"{a}={env[a]}" for a in args)
                out.append(
                    Diagnostic(
                        ctx.relpath, fn.lineno, 0, code,
                        f"{name}({at}) = {result!r} violates declared "
                        f"property {prop!r}",
                    )
                )
        return out

    # -- declared constants --------------------------------------------------

    def _check_consts(self, ctx, consts) -> list[Diagnostic]:
        out: list[Diagnostic] = []
        if not isinstance(consts, dict):
            return [
                Diagnostic(
                    ctx.relpath, 1, 0, "DS1300",
                    "SPMD_CONTRACT['consts'] must be a dict",
                )
            ]
        if not consts:
            return []
        ev = Evaluator()
        env = module_const_env(ctx.tree, ev)
        lines = self._const_lines(ctx.tree)
        for name, require in sorted(consts.items()):
            if name not in env:
                out.append(
                    Diagnostic(
                        ctx.relpath, 1, 0, "DS1300",
                        f"declared constant {name!r} not found (or not "
                        "statically evaluable) at module top level",
                    )
                )
                continue
            if not isinstance(require, (list, tuple)) or not all(
                isinstance(r, (list, tuple))
                and len(r) == 2
                and r[0] in self.codes
                for r in require
            ):
                out.append(
                    Diagnostic(
                        ctx.relpath, lines.get(name, 1), 0, "DS1300",
                        f"consts[{name!r}] needs (code, property) pairs",
                    )
                )
                continue
            for code, prop in require:
                try:
                    ok = ev.eval_str(prop, {**env, "value": env[name]})
                except EvalError as e:
                    out.append(
                        Diagnostic(
                            ctx.relpath, lines.get(name, 1), 0, "DS1300",
                            f"consts[{name!r}] property {prop!r} is not "
                            f"evaluable: {e}",
                        )
                    )
                    continue
                if not ok:
                    out.append(
                        Diagnostic(
                            ctx.relpath, lines.get(name, 1), 0, code,
                            f"constant {name} = {env[name]!r} violates "
                            f"declared property {prop!r}",
                        )
                    )
        return out

    @staticmethod
    def _const_lines(tree) -> dict[str, int]:
        out: dict[str, int] = {}
        for node in getattr(tree, "body", []):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, node.lineno)
        return out

    # -- declared canvas stores (the re-pack hop) ----------------------------

    def _check_stores(self, ctx, stores) -> list[Diagnostic]:
        out: list[Diagnostic] = []
        if not isinstance(stores, dict):
            return [
                Diagnostic(
                    ctx.relpath, 1, 0, "DS1300",
                    "SPMD_CONTRACT['stores'] must be a dict",
                )
            ]
        functions = extract_functions(ctx.tree)
        for name, specs in sorted(stores.items()):
            fn = functions.get(name)
            if fn is None:
                out.append(
                    Diagnostic(
                        ctx.relpath, 1, 0, "DS1300",
                        f"declared store function {name!r} not found at "
                        "module top level",
                    )
                )
                continue
            if not isinstance(specs, (list, tuple)):
                out.append(
                    Diagnostic(
                        ctx.relpath, fn.lineno, 0, "DS1300",
                        f"stores[{name!r}] must be a tuple of store specs",
                    )
                )
                continue
            for spec in specs:
                if not isinstance(spec, dict) or not all(
                    isinstance(spec.get(k), str)
                    for k in ("canvas", "repack", "width")
                ):
                    out.append(
                        Diagnostic(
                            ctx.relpath, fn.lineno, 0, "DS1300",
                            f"stores[{name!r}] specs need canvas/repack/"
                            "width names",
                        )
                    )
                    continue
                out.extend(self._check_store(ctx, fn, spec))
        return out

    def _check_store(self, ctx, fn, spec) -> list[Diagnostic]:
        canvas, repack, width = (
            spec["canvas"], spec["repack"], spec["width"],
        )
        sets = [
            node
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "set"
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"
            and isinstance(node.func.value.value.value, ast.Name)
            and node.func.value.value.value.id == canvas
        ]
        if not sets:
            return [
                Diagnostic(
                    ctx.relpath, fn.lineno, 0, "DS1300",
                    f"{fn.name}: no {canvas}.at[...].set(...) store found "
                    "(stale stores declaration?)",
                )
            ]
        out = []
        for node in sets:
            repacked = any(
                isinstance(n, ast.Call)
                and callee_basename(n.func) == repack
                and any(
                    isinstance(a, ast.Name) and a.id == width
                    for a in n.args
                )
                for a in node.args
                for n in ast.walk(a)
            )
            if not repacked:
                out.append(
                    Diagnostic(
                        ctx.relpath, node.lineno, node.col_offset, "DS1302",
                        f"{fn.name}: store into receive canvas {canvas!r} "
                        f"without the declared {repack}(..., {width}, ...) "
                        "re-pack — a short leg buffer would land in a "
                        f"{width}-wide row",
                    )
                )
        return out
