"""Checker registry: every project-native rule, one instance each.

Adding a checker = adding a class with ``name``/``codes``/``scope``/``check``
(or ``project = True`` + ``check_project`` for a cross-file rule) and
listing it here; the engine, CLI, docs catalog and the lint tests pick
it up from this one function.
"""

from __future__ import annotations

from dsort_tpu.analysis.checkers.caps import CapsChecker
from dsort_tpu.analysis.checkers.compat import CompatChecker
from dsort_tpu.analysis.checkers.concurrency import ConcurrencyChecker
from dsort_tpu.analysis.checkers.durability import DurabilityChecker
from dsort_tpu.analysis.checkers.exceptions import ExceptionsChecker
from dsort_tpu.analysis.checkers.layers import LayersChecker
from dsort_tpu.analysis.checkers.lifecycle import LifecycleChecker
from dsort_tpu.analysis.checkers.protocol import ProtocolChecker
from dsort_tpu.analysis.checkers.registry import RegistryChecker
from dsort_tpu.analysis.checkers.spec import SpecChecker
from dsort_tpu.analysis.checkers.spmd import SpmdChecker
from dsort_tpu.analysis.checkers.tracing import TracingChecker


def all_checkers():
    return [
        RegistryChecker(),
        ConcurrencyChecker(),
        TracingChecker(),
        ExceptionsChecker(),
        CompatChecker(),
        LayersChecker(),
        DurabilityChecker(),
        ProtocolChecker(),
        LifecycleChecker(),
        SpecChecker(),
        SpmdChecker(),
        CapsChecker(),
    ]


def checker_catalog() -> dict[str, dict[str, str]]:
    """{checker name: {code: description}} — the documented rule set."""
    return {c.name: dict(c.codes) for c in all_checkers()}
