"""SPMD semantics verifier: the symbolic plane behind the DS12xx/DS13xx rules.

The exchange variants are built on hand-derived ``ppermute`` tables, static
receive-slot offsets, and a no-retry capacity doctrine ("ring-path overflow
is an invariant violation") that — before this package — was enforced only
at runtime by drills.  Schedule-synthesized redistribution (arXiv:2112.01075)
treats collective schedules as verifiable objects; this package gives the
lint pass the machinery to do the same statically:

- `symeval`: a restricted, stdlib-only evaluator for the pure closed-form
  functions the schedules are built from (perm builders, cap quantizers,
  slot-offset cumsums).  It interprets the AST of THE TREE BEING LINTED —
  never imports it — so the verdict is about what is written, not about an
  installed copy, and linting never initializes a JAX backend.
- `registry`: the pure-literal declaration registry — bounded verification
  domains (mesh sizes, size samples, caps samples), the modules REQUIRED to
  carry an ``SPMD_CONTRACT``, and the minimum each contract must declare
  (so deleting a declaration cannot silence a proof — the same
  no-vacuous-pass doctrine as the spec plane's DS1001).
- `contract`: extraction of per-module ``SPMD_CONTRACT`` literals and the
  domain-grid iteration shared by both checker families.

The checkers themselves live in `dsort_tpu.analysis.checkers.spmd` (DS12xx,
collective schedules) and `dsort_tpu.analysis.checkers.caps` (DS13xx,
capacity/layout interval checks); ARCHITECTURE.md §19 documents the catalog
and the honest limits of the bounded symbolic evaluation.
"""

from __future__ import annotations

from dsort_tpu.analysis.spmd.contract import (
    ContractError,
    extract_contract,
    load_spmd_registry,
)
from dsort_tpu.analysis.spmd.symeval import EvalError, Evaluator, extract_functions

__all__ = [
    "ContractError",
    "EvalError",
    "Evaluator",
    "extract_contract",
    "extract_functions",
    "load_spmd_registry",
]
