"""THE SPMD-verifier declaration registry (pure literals, parsed not run).

Two things live here, both read by PARSING this file (`ast.literal_eval`),
never by importing it — the same discipline as every other lint registry:

1. **Bounded verification domains.**  The symbolic proofs are exhaustive
   over these concrete grids (the small-scope doctrine the spec plane's
   model checker established): mesh sizes ``P``, local-shard size samples,
   and caps tuples for the slot-layout checks.  Growing a grid strengthens
   every proof at once; the grids are part of the lint cache key, so
   editing them invalidates cached verdicts.

2. **Required declarations.**  Each module in `SPMD_REQUIRED` must carry a
   top-level pure-literal ``SPMD_CONTRACT``; the per-file minima below pin
   what that contract must at least declare.  This is the no-vacuous-pass
   doctrine: deleting a contract (or one entry of it) to silence a proof
   is itself a DS1200/DS1300 finding, so the seeded-mutation gates cannot
   be dodged by removing the declaration they check against.

The lint `ResultCache` hashes this file AND every source it names into the
config key (`engine.ResultCache._config_key`): editing a closed form in
``exchange.py`` invalidates every cached verdict in the tree.
"""

#: Mesh-axis sizes ``P`` the permutation/layout proofs instantiate.  Covers
#: the degenerate 1-device mesh, primes (no host grouping), and the
#: composite sizes the hierarchical plane actually groups (H x D).
MESH_SIZES = (1, 2, 3, 4, 6, 8)

#: Local-shard sizes ``n_local`` the capacity proofs sweep measured maxes
#: over (the sweep stride adapts; edges are always included).
SIZE_SAMPLES = (8, 64, 100, 1000, 4096, 100000)

#: Caps tuples driving the slot-offset/cumsum layout proofs.  Mixed rungs,
#: a zero-length slot, and a single-slot degenerate all participate.
CAPS_SAMPLES = (
    (8,),
    (8, 16),
    (8, 0, 16),
    (8, 16, 8, 24),
    (16, 8, 8, 32, 8, 40, 8, 8),
)

#: Modules that MUST declare a top-level ``SPMD_CONTRACT``.
SPMD_REQUIRED = (
    "dsort_tpu/parallel/exchange.py",
    "dsort_tpu/parallel/coded.py",
    "dsort_tpu/ops/ring_kernel.py",
    "dsort_tpu/models/pipelines.py",
    "dsort_tpu/obs/plan.py",
)

#: Per-file minimum ``perms`` declarations (DS12xx): the closed-form
#: ppermute builders that must stay declared and proven.
SPMD_REQUIRED_PERMS = {
    "dsort_tpu/parallel/exchange.py": (
        "_ring_perm",
        "_hier_perm_intra",
        "_hier_perm_leg",
    ),
}

#: Per-file minimum ``layouts`` declarations (DS1204): fused kernels whose
#: remote-DMA write regions must stay provably disjoint.
SPMD_REQUIRED_LAYOUTS = {
    "dsort_tpu/ops/ring_kernel.py": (
        "_fused_ring_kernel",
        "_fused_ring_kv_kernel",
    ),
}

#: Per-file minimum ``caps`` declarations (DS13xx).
SPMD_REQUIRED_CAPS = {
    "dsort_tpu/parallel/exchange.py": (
        "ring_step_quantum",
        "_quantize_cap",
        "ladder_rungs",
        "parity_slots",
        "resolve_redundancy",
    ),
    "dsort_tpu/ops/ring_kernel.py": ("_step_offsets",),
    "dsort_tpu/models/pipelines.py": ("pad_rung",),
}

#: Per-file minimum ``stores`` declarations (DS1302): receive-canvas writes
#: that must keep their declared re-pack hop.
SPMD_REQUIRED_STORES = {
    "dsort_tpu/parallel/exchange.py": ("_hier_exchange_shard",),
}

#: Per-file minimum ``consts`` declarations (DS1303 clamp chains).
SPMD_REQUIRED_CONSTS = {
    "dsort_tpu/obs/plan.py": ("WAVE_MIN_ELEMS", "WAVE_MAX_ELEMS"),
}

#: Mesh-axis-name vocabulary collectives may name literally (DS1203), and
#: the sources whose mesh-construction defaults must actually define each
#: name — `parallel.mesh.make_mesh` builds its ``Mesh`` from these config
#: fields, so an axis in this tuple IS an axis some mesh is constructed
#: with.
MESH_AXES = ("w", "dp")
MESH_AXIS_SOURCES = ("dsort_tpu/config.py",)
