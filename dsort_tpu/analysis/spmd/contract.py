"""Per-module ``SPMD_CONTRACT`` extraction and verification-domain plumbing.

An ``SPMD_CONTRACT`` is a top-level PURE-LITERAL dict a module declares
about its own SPMD surface — which plane it is on (``device``/``host``),
its closed-form perm builders and their expected destination forms, its
fused-kernel DMA layouts, its capacity functions with the properties each
must satisfy, and its receive-canvas re-pack obligations.  The checkers
(`checkers.spmd`, `checkers.caps`) PROVE the module against its contract
over the bounded domains in `spmd.registry`; the registry's per-file
minima make sure the contract cannot quietly shrink.
"""

from __future__ import annotations

import ast

from dsort_tpu.analysis.spmd.symeval import EvalError, Evaluator


class ContractError(Exception):
    """A contract/registry is present but not a usable pure literal."""

    def __init__(self, lineno: int, msg: str):
        super().__init__(msg)
        self.lineno = lineno


#: The only keys a contract may carry (typo'd sections must not silently
#: verify nothing).
CONTRACT_KEYS = {
    "plane",
    "axis_param",
    "perms",
    "layouts",
    "caps",
    "stores",
    "consts",
}

#: Registry names `load_spmd_registry` requires, with the type each must be.
_REGISTRY_SHAPE = {
    "MESH_SIZES": tuple,
    "SIZE_SAMPLES": tuple,
    "CAPS_SAMPLES": tuple,
    "SPMD_REQUIRED": tuple,
    "SPMD_REQUIRED_PERMS": dict,
    "SPMD_REQUIRED_LAYOUTS": dict,
    "SPMD_REQUIRED_CAPS": dict,
    "SPMD_REQUIRED_STORES": dict,
    "SPMD_REQUIRED_CONSTS": dict,
    "MESH_AXES": tuple,
    "MESH_AXIS_SOURCES": tuple,
}


def extract_contract(tree: ast.AST) -> tuple[dict | None, int]:
    """The module's ``SPMD_CONTRACT`` literal and its line, else (None, 0).

    Raises `ContractError` when the assignment exists but is not a pure
    literal dict — a computed contract cannot be verified without running
    the tree, which the analysis plane never does.
    """
    for node in getattr(tree, "body", []):
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "SPMD_CONTRACT":
                try:
                    lit = ast.literal_eval(value)
                except (ValueError, SyntaxError, TypeError):
                    raise ContractError(
                        node.lineno,
                        "SPMD_CONTRACT must be a pure literal dict",
                    ) from None
                if not isinstance(lit, dict):
                    raise ContractError(
                        node.lineno, "SPMD_CONTRACT must be a dict"
                    )
                return lit, node.lineno
    return None, 0


def load_spmd_registry(path: str) -> dict:
    """Parse the declaration registry into ``{name: literal}``.

    Raises `ContractError` (anchored to the offending line, or 1) when the
    file is unreadable, unparseable, or misses a required declaration —
    the checkers turn that into a loud DS1200/DS1300, never a silent pass.
    """
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except OSError:
        raise ContractError(1, f"spmd registry unreadable: {path}") from None
    except SyntaxError as e:
        raise ContractError(
            e.lineno or 1, f"spmd registry syntax error: {e.msg}"
        ) from None
    out: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                try:
                    out[t.id] = ast.literal_eval(node.value)
                except (ValueError, SyntaxError, TypeError):
                    raise ContractError(
                        node.lineno,
                        f"registry declaration {t.id} is not a pure literal",
                    ) from None
    for name, kind in _REGISTRY_SHAPE.items():
        if not isinstance(out.get(name), kind):
            raise ContractError(
                1, f"registry misses {name} (expected {kind.__name__})"
            )
    return out


#: Domain-expression tokens that resolve to registry grids.
_DOMAIN_TOKENS = {
    "MESH": "MESH_SIZES",
    "SIZES": "SIZE_SAMPLES",
    "CAPS_SAMPLES": "CAPS_SAMPLES",
}


def iter_domain(domain: dict, registry: dict, ev: Evaluator):
    """Yield one env dict per point of the (ordered) domain product.

    Each value is either a registry token (``"MESH"``/``"SIZES"``/
    ``"CAPS_SAMPLES"``) or a Python expression over the names bound so far
    (``"range(num_workers)"``, ``"[d for d in ... if num_workers % d == 0]"``)
    evaluated by the restricted evaluator.  Raises `EvalError` on a domain
    expression outside the evaluable subset.
    """
    names = list(domain)

    def rec(i: int, env: dict):
        if i == len(names):
            yield dict(env)
            return
        name = names[i]
        spec = domain[name]
        if not isinstance(spec, str):
            raise EvalError(f"domain for {name!r} must be a string")
        token = _DOMAIN_TOKENS.get(spec)
        values = (
            registry[token] if token else ev.eval_str(spec, env)
        )
        if not isinstance(values, (list, tuple, range)):
            raise EvalError(f"domain for {name!r} is not a sequence")
        for v in values:
            env[name] = v
            yield from rec(i + 1, env)
        env.pop(name, None)

    yield from rec(0, {})


def module_const_env(tree: ast.AST, ev: Evaluator) -> dict:
    """Best-effort env of a module's top-level constant assignments.

    Evaluates each top-level ``NAME = <expr>`` with the restricted
    evaluator against the names bound so far (so ``1 << 18`` and derived
    constants resolve); unevaluable assignments are simply skipped — the
    consts checks report a missing name loudly.
    """
    env: dict = {}
    for node in getattr(tree, "body", []):
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                try:
                    env[t.id] = ev.eval_expr(value, env)
                except EvalError:
                    pass
    return env
