"""Restricted symbolic evaluator for closed-form schedule arithmetic.

Evaluates the PURE integer/list functions the exchange schedules are built
from — perm builders (`_ring_perm`, `_hier_perm_intra`, `_hier_perm_leg`),
cap quantizers (`ring_step_quantum`, `_quantize_cap`, `ladder_rungs`,
`pad_rung`, `parity_slots`) and slot-offset cumsums (`_step_offsets`) — by
interpreting their AST directly.  Nothing is imported from the tree being
linted: the verdict is about the source text, and a lint run must never
initialize a JAX backend (the analysis package is stdlib-only by layer
contract).

The evaluator is deliberately SMALL.  It supports exactly the statement and
expression shapes those closed forms use (arithmetic, comparisons,
comprehensions, ``for``/``while``/``if``, calls to a builtin whitelist and
to other module-level functions) and raises `EvalError` on anything else —
a function that drifts outside the evaluable subset is reported loudly
(DS1200/DS1300), never silently skipped.  A global step budget bounds every
evaluation, so a seeded non-terminating mutation degrades to a loud
"not statically evaluable" finding rather than a hung lint run.
"""

from __future__ import annotations

import ast


class EvalError(Exception):
    """The expression/function left the evaluable subset (or the budget)."""


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


#: Builtins the closed forms may call.  ``print``/``getattr``/imports are
#: deliberately absent: anything effectful or reflective is out of scope.
_BUILTINS = {
    "abs": abs,
    "all": all,
    "any": any,
    "bool": bool,
    "divmod": divmod,
    "enumerate": enumerate,
    "int": int,
    "len": len,
    "list": list,
    "max": max,
    "min": min,
    "range": range,
    "reversed": reversed,
    "sorted": sorted,
    "sum": sum,
    "tuple": tuple,
    "zip": zip,
}

#: Methods callable on evaluated values, by value type.
_METHODS = {
    int: {"bit_length"},
    list: {"append", "extend", "pop", "index", "count"},
    tuple: {"index", "count"},
}

_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a**b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitXor: lambda a, b: a ^ b,
}

_CMPOPS = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.Is: lambda a, b: a is b,
    ast.IsNot: lambda a, b: a is not b,
    ast.In: lambda a, b: a in b,
    ast.NotIn: lambda a, b: a not in b,
}


def extract_functions(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    """Top-level function definitions of a parsed module, by name."""
    out: dict[str, ast.FunctionDef] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.FunctionDef):
            out[node.name] = node
    return out


class Evaluator:
    """Interpret closed forms over concrete instantiations.

    ``functions`` maps name -> top-level ``ast.FunctionDef`` of the module
    under analysis; calls between them resolve through this table (e.g.
    ``_quantize_cap`` -> ``ring_step_quantum``).  ``max_steps`` is a global
    budget across nested calls.
    """

    def __init__(
        self,
        functions: dict[str, ast.FunctionDef] | None = None,
        max_steps: int = 2_000_000,
    ):
        self.functions = functions or {}
        self.max_steps = max_steps
        self.steps = 0

    # -- entry points -------------------------------------------------------

    def call(self, name: str, args: list, kwargs: dict | None = None):
        fn = self.functions.get(name)
        if fn is None:
            raise EvalError(f"unknown function {name!r}")
        return self._call_def(fn, args, kwargs or {})

    def eval_str(self, expr: str, env: dict):
        """Evaluate a Python expression string against ``env``."""
        try:
            node = ast.parse(expr, mode="eval")
        except SyntaxError as e:
            raise EvalError(f"bad expression {expr!r}: {e.msg}") from None
        return self.eval_expr(node.body, env)

    # -- plumbing -----------------------------------------------------------

    def _tick(self):
        self.steps += 1
        if self.steps > self.max_steps:
            raise EvalError("evaluation step budget exceeded")

    def _call_def(self, fn: ast.FunctionDef, args: list, kwargs: dict):
        a = fn.args
        if a.vararg or a.kwarg or a.posonlyargs:
            raise EvalError(f"{fn.name}: unsupported signature")
        names = [x.arg for x in a.args] + [x.arg for x in a.kwonlyargs]
        env: dict = {}
        if len(args) > len(a.args):
            raise EvalError(f"{fn.name}: too many positional args")
        for name, val in zip([x.arg for x in a.args], args):
            env[name] = val
        for key, val in kwargs.items():
            if key not in names:
                raise EvalError(f"{fn.name}: unknown kwarg {key!r}")
            env[key] = val
        # Defaults for anything still unbound.
        pos_defaults = dict(
            zip([x.arg for x in a.args][len(a.args) - len(a.defaults):],
                a.defaults)
        )
        kw_defaults = {
            x.arg: d
            for x, d in zip(a.kwonlyargs, a.kw_defaults)
            if d is not None
        }
        for name in names:
            if name not in env:
                default = pos_defaults.get(name, kw_defaults.get(name))
                if default is None:
                    raise EvalError(f"{fn.name}: missing argument {name!r}")
                env[name] = self.eval_expr(default, env)
        try:
            self._exec(fn.body, env)
        except _Return as r:
            return r.value
        return None

    def _exec(self, stmts: list[ast.stmt], env: dict) -> None:
        for node in stmts:
            self._tick()
            if isinstance(node, ast.Return):
                raise _Return(
                    None if node.value is None
                    else self.eval_expr(node.value, env)
                )
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._assign(node, env)
            elif isinstance(node, ast.If):
                branch = (
                    node.body if self.eval_expr(node.test, env) else node.orelse
                )
                self._exec(branch, env)
            elif isinstance(node, ast.For):
                self._for(node, env)
            elif isinstance(node, ast.While):
                while self.eval_expr(node.test, env):
                    self._tick()
                    try:
                        self._exec(node.body, env)
                    except _Break:
                        break
                    except _Continue:
                        continue
            elif isinstance(node, ast.Expr):
                self.eval_expr(node.value, env)
            elif isinstance(node, ast.Pass):
                pass
            elif isinstance(node, ast.Break):
                raise _Break()
            elif isinstance(node, ast.Continue):
                raise _Continue()
            elif isinstance(node, ast.Raise):
                # The closed forms raise only on domain violations; reaching
                # one under a verification domain IS a verification failure.
                raise EvalError("explicit raise reached during evaluation")
            elif isinstance(node, ast.Assert):
                if not self.eval_expr(node.test, env):
                    raise EvalError("assert failed during evaluation")
            else:
                raise EvalError(
                    f"unsupported statement {type(node).__name__}"
                )

    def _for(self, node: ast.For, env: dict) -> None:
        if node.orelse:
            raise EvalError("for/else unsupported")
        for item in self._iter(self.eval_expr(node.iter, env)):
            self._tick()
            self._bind(node.target, item, env)
            try:
                self._exec(node.body, env)
            except _Break:
                break
            except _Continue:
                continue

    @staticmethod
    def _iter(value):
        if isinstance(value, (list, tuple, range, str)) or hasattr(
            value, "__next__"
        ):
            return value
        raise EvalError(f"not iterable: {type(value).__name__}")

    def _assign(self, node, env: dict) -> None:
        if isinstance(node, ast.AugAssign):
            if not isinstance(node.target, ast.Name):
                raise EvalError("augmented assign to non-name")
            op = _BINOPS.get(type(node.op))
            if op is None:
                raise EvalError("unsupported augmented op")
            cur = self._load_name(node.target.id, env)
            env[node.target.id] = op(cur, self.eval_expr(node.value, env))
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is None:
                raise EvalError("annotation without value")
            targets = [node.target]
            value = self.eval_expr(node.value, env)
        else:
            targets = node.targets
            value = self.eval_expr(node.value, env)
        for t in targets:
            self._bind(t, value, env)

    def _bind(self, target, value, env: dict) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            vals = list(self._iter(value))
            if len(vals) != len(target.elts):
                raise EvalError("unpack length mismatch")
            for t, v in zip(target.elts, vals):
                self._bind(t, v, env)
        elif isinstance(target, ast.Subscript):
            obj = self.eval_expr(target.value, env)
            if not isinstance(obj, list):
                raise EvalError("subscript assignment to non-list")
            obj[self._index(target.slice, env)] = value
        else:
            raise EvalError(
                f"unsupported assignment target {type(target).__name__}"
            )

    def _load_name(self, name: str, env: dict):
        if name in env:
            return env[name]
        if name in ("True", "False", "None"):  # pre-3.8 trees only
            return {"True": True, "False": False, "None": None}[name]
        raise EvalError(f"unbound name {name!r}")

    def _index(self, node, env):
        return self.eval_expr(node, env)

    # -- expressions --------------------------------------------------------

    def eval_expr(self, node: ast.expr, env: dict):
        self._tick()
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, bool, str)) or node.value is None:
                return node.value
            raise EvalError(f"unsupported constant {node.value!r}")
        if isinstance(node, ast.Name):
            return self._load_name(node.id, env)
        if isinstance(node, ast.BinOp):
            op = _BINOPS.get(type(node.op))
            if op is None:
                raise EvalError(
                    f"unsupported operator {type(node.op).__name__}"
                )
            try:
                return op(
                    self.eval_expr(node.left, env),
                    self.eval_expr(node.right, env),
                )
            except (TypeError, ZeroDivisionError, ValueError) as e:
                raise EvalError(str(e)) from None
        if isinstance(node, ast.UnaryOp):
            val = self.eval_expr(node.operand, env)
            if isinstance(node.op, ast.USub):
                return -val
            if isinstance(node.op, ast.UAdd):
                return +val
            if isinstance(node.op, ast.Not):
                return not val
            if isinstance(node.op, ast.Invert):
                return ~val
            raise EvalError("unsupported unary op")
        if isinstance(node, ast.BoolOp):
            if isinstance(node.op, ast.And):
                val = True
                for v in node.values:
                    val = self.eval_expr(v, env)
                    if not val:
                        return val
                return val
            val = False
            for v in node.values:
                val = self.eval_expr(v, env)
                if val:
                    return val
            return val
        if isinstance(node, ast.Compare):
            left = self.eval_expr(node.left, env)
            for op, rhs in zip(node.ops, node.comparators):
                fn = _CMPOPS.get(type(op))
                if fn is None:
                    raise EvalError("unsupported comparison")
                right = self.eval_expr(rhs, env)
                try:
                    ok = fn(left, right)
                except TypeError as e:
                    raise EvalError(str(e)) from None
                if not ok:
                    return False
                left = right
            return True
        if isinstance(node, ast.IfExp):
            return (
                self.eval_expr(node.body, env)
                if self.eval_expr(node.test, env)
                else self.eval_expr(node.orelse, env)
            )
        if isinstance(node, ast.Tuple):
            return tuple(self.eval_expr(e, env) for e in node.elts)
        if isinstance(node, ast.List):
            return [self.eval_expr(e, env) for e in node.elts]
        if isinstance(node, ast.Subscript):
            obj = self.eval_expr(node.value, env)
            if isinstance(node.slice, ast.Slice):
                s = node.slice
                lo = None if s.lower is None else self.eval_expr(s.lower, env)
                hi = None if s.upper is None else self.eval_expr(s.upper, env)
                st = None if s.step is None else self.eval_expr(s.step, env)
                try:
                    return obj[lo:hi:st]
                except TypeError as e:
                    raise EvalError(str(e)) from None
            idx = self.eval_expr(node.slice, env)
            try:
                return obj[idx]
            except (TypeError, IndexError, KeyError) as e:
                raise EvalError(str(e)) from None
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            out = self._comprehension(node, env)
            if isinstance(node, ast.SetComp):
                return set(out)
            return out
        if isinstance(node, ast.Call):
            return self._call(node, env)
        raise EvalError(f"unsupported expression {type(node).__name__}")

    def _comprehension(self, node, env: dict) -> list:
        out: list = []
        scope = dict(env)

        def rec(gens: list[ast.comprehension]):
            gen = gens[0]
            if gen.is_async:
                raise EvalError("async comprehension")
            for item in self._iter(self.eval_expr(gen.iter, scope)):
                self._tick()
                self._bind(gen.target, item, scope)
                if not all(
                    self.eval_expr(cond, scope) for cond in gen.ifs
                ):
                    continue
                if len(gens) > 1:
                    rec(gens[1:])
                else:
                    out.append(self.eval_expr(node.elt, scope))

        rec(node.generators)
        return out

    def _call(self, node: ast.Call, env: dict):
        for kw in node.keywords:
            if kw.arg is None:
                raise EvalError("**kwargs call unsupported")
        if any(isinstance(a, ast.Starred) for a in node.args):
            raise EvalError("*args call unsupported")
        args = [self.eval_expr(a, env) for a in node.args]
        kwargs = {
            kw.arg: self.eval_expr(kw.value, env) for kw in node.keywords
        }
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in env:
                raise EvalError(f"call through variable {func.id!r}")
            if func.id in _BUILTINS:
                try:
                    return _BUILTINS[func.id](*args, **kwargs)
                except (TypeError, ValueError) as e:
                    raise EvalError(str(e)) from None
            if func.id in self.functions:
                return self._call_def(self.functions[func.id], args, kwargs)
            raise EvalError(f"call to unknown function {func.id!r}")
        if isinstance(func, ast.Attribute):
            obj = self.eval_expr(func.value, env)
            allowed = _METHODS.get(type(obj), set())
            if func.attr not in allowed:
                raise EvalError(
                    f"method {type(obj).__name__}.{func.attr} unsupported"
                )
            try:
                return getattr(obj, func.attr)(*args, **kwargs)
            except (TypeError, ValueError, IndexError) as e:
                raise EvalError(str(e)) from None
        raise EvalError("unsupported call target")
