"""A small line-oriented C++ lexer — just enough to scan the native runtime.

Full C++ parsing is out of scope (and out of proportion: the registry
checker only needs to see which string literals flow into
``log_event_locked``).  This lexer handles exactly the constructs that would
otherwise produce false tokens: ``//`` and ``/* */`` comments, string and
character literals (with escapes), and raw strings ``R"(...)"`` — and emits
a flat token stream of identifiers, string literals, and single-character
punctuation with 1-based line numbers.
"""

from __future__ import annotations

import dataclasses
import re

IDENT = "ident"
STRING = "string"
PUNCT = "punct"


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # IDENT | STRING | PUNCT
    value: str  # STRING tokens hold the *decoded* literal text
    line: int  # 1-based


_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_SIMPLE_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "0": "\0", "\\": "\\",
    '"': '"', "'": "'",
}


def tokenize(source: str) -> list[Token]:
    toks: list[Token] = []
    i, n, line = 0, len(source), 1
    while i < n:
        c = source[i]
        if c == "\n":
            line += 1
            i += 1
        elif c in " \t\r\f\v":
            i += 1
        elif source.startswith("//", i):
            j = source.find("\n", i)
            i = n if j < 0 else j  # newline handled above (keeps line count)
        elif source.startswith("/*", i):
            j = source.find("*/", i + 2)
            j = n if j < 0 else j + 2
            line += source.count("\n", i, j)
            i = j
        elif source.startswith('R"', i):
            # Raw string: R"delim( ... )delim"
            m = re.match(r'R"([^()\\ ]*)\(', source[i:])
            if m is None:
                toks.append(Token(PUNCT, c, line))
                i += 1
                continue
            close = f"){m.group(1)}\""
            j = source.find(close, i + m.end())
            j = n if j < 0 else j
            body = source[i + m.end() : j]
            toks.append(Token(STRING, body, line))
            line += source.count("\n", i, j)
            i = min(j + len(close), n)
        elif c in "\"'":
            quote, j, out = c, i + 1, []
            while j < n and source[j] != quote:
                if source[j] == "\\" and j + 1 < n:
                    out.append(_SIMPLE_ESCAPES.get(source[j + 1], source[j + 1]))
                    j += 2
                else:
                    out.append(source[j])
                    j += 1
            if c == '"':
                toks.append(Token(STRING, "".join(out), line))
            line += source.count("\n", i, j)
            i = j + 1
        else:
            m = _IDENT_RE.match(source, i)
            if m:
                toks.append(Token(IDENT, m.group(), line))
                i = m.end()
            else:
                toks.append(Token(PUNCT, c, line))
                i += 1
    return toks


def call_string_args(source: str, callee: str) -> list[Token]:
    """First string-literal argument of every ``callee(...)`` call.

    Scans the token stream for ``callee`` followed by ``(`` and returns the
    first STRING token before the matching close paren (calls whose first
    string sits in a nested call are fine: the event-name argument is by
    convention the literal closest to the open paren).  Calls with no string
    literal at all contribute nothing.
    """
    toks = tokenize(source)
    out: list[Token] = []
    for i, t in enumerate(toks):
        if t.kind != IDENT or t.value != callee:
            continue
        nxt = toks[i + 1] if i + 1 < len(toks) else None
        if nxt is None or nxt.kind != PUNCT or nxt.value != "(":
            continue
        depth = 0
        for u in toks[i + 1 :]:
            if u.kind == PUNCT and u.value == "(":
                depth += 1
            elif u.kind == PUNCT and u.value == ")":
                depth -= 1
                if depth == 0:
                    break
            elif u.kind == STRING:
                out.append(u)
                break
    return out
