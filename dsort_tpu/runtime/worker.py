"""Worker shim: joins a coordinator's cluster and serves sort tasks.

The successor of the reference worker (``client.c``): connect to the master
(``client.c:68-86``), loop receiving work, sort locally, send the result back
(``client.c:90-137``).  Differences, by design:

- frames are length-prefixed (u32 type | u32 task_id | u64 len) instead of
  ``-1``-sentinel int32 pages, so no key value is reserved;
- the local sort is a jitted JAX sort on the worker's accelerator (the
  TPU-native replacement of the recursive mallocing merge sort at
  ``client.c:140-173``); ``--backend numpy`` exists for light-weight tests;
- a heartbeat thread reports liveness, so a hung worker is detectable
  (the reference has no heartbeat at all, SURVEY.md §5.3).

Run: ``python -m dsort_tpu.runtime.worker --host 127.0.0.1 --port 9008``
(defaults match the reference's ``client.conf``; ``--conf client.conf``
parses the reference's own file format).
"""

from __future__ import annotations

import argparse
import socket
import struct
import threading

import numpy as np

_HDR = struct.Struct("<IIQ")  # type, task_id, len — matches coordinator.cpp
T_TASK, T_RESULT, T_HEARTBEAT, T_SHUTDOWN = 1, 2, 3, 4


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class SortWorker:
    """One worker process: receive chunk -> local sort -> send back."""

    def __init__(
        self,
        host: str,
        port: int,
        dtype="int32",
        backend: str = "jax",
        heartbeat_interval_s: float = 1.0,
        connect_timeout_s: float = 30.0,
        kernel: str = "auto",
    ):
        self.host = host
        self.port = port
        self.dtype = np.dtype(dtype)
        self.backend = backend
        self.heartbeat_interval_s = heartbeat_interval_s
        self.connect_timeout_s = connect_timeout_s
        self._sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self._stop = threading.Event()
        if backend == "jax":
            import jax

            if self.dtype.itemsize == 8:
                # Without x64 mode JAX silently downcasts int64/uint64 inputs
                # to 32-bit — the sorted result frame would come back
                # half-length and value-truncated.  This worker is its own
                # entrypoint (never passes through cli.main), so it must
                # enable x64 itself — via the compat shim (DS501).
                from dsort_tpu.utils.compat import set_x64

                set_x64(True)
            # The worker owns its kernel (client.c:140-173): ``auto`` routes
            # to the block kernel on a TPU-attached worker, lax elsewhere.
            from dsort_tpu.ops.local_sort import sort_with_kernel

            self._jit_sort = jax.jit(lambda x: sort_with_kernel(x, kernel))
        else:
            self._jit_sort = None

    def _sort(self, arr: np.ndarray) -> np.ndarray:
        if self._jit_sort is not None:
            return np.asarray(self._jit_sort(arr))
        return np.sort(arr, kind="stable")

    def _send_frame(self, ftype: int, task_id: int, payload: bytes = b"") -> None:
        with self._send_lock:
            self._sock.sendall(_HDR.pack(ftype, task_id, len(payload)))
            if payload:
                self._sock.sendall(payload)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._send_frame(T_HEARTBEAT, 0)
            except OSError:
                return

    def _connect_with_retry(self) -> socket.socket:
        # The reference client exits on a failed connect (client.c:82-86);
        # retrying makes cluster formation order-independent.
        import time

        deadline = time.monotonic() + self.connect_timeout_s
        while True:
            try:
                return socket.create_connection((self.host, self.port), timeout=5.0)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.3)

    def serve_forever(self) -> None:
        self._sock = self._connect_with_retry()
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        hb.start()
        try:
            while True:
                hdr = _read_exact(self._sock, _HDR.size)
                if hdr is None:
                    return  # server closed (client.c:97-100 analogue)
                ftype, task_id, length = _HDR.unpack(hdr)
                if ftype == T_SHUTDOWN:
                    return
                if ftype != T_TASK:
                    continue
                payload = _read_exact(self._sock, length) if length else b""
                if payload is None:
                    return
                arr = np.frombuffer(payload, dtype=self.dtype)
                out = self._sort(arr)
                self._send_frame(T_RESULT, task_id, out.tobytes())
        finally:
            self._stop.set()
            try:
                self._sock.close()
            except OSError:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="dsort_tpu worker shim")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9008)  # client.conf default
    ap.add_argument("--conf", help="reference-format client.conf (SERVER_IP/SERVER_PORT)")
    ap.add_argument("--dtype", default="int32")
    ap.add_argument("--backend", choices=["jax", "numpy"], default="jax")
    ap.add_argument("--kernel", default="auto",
                    choices=["auto", "lax", "block", "bitonic", "pallas", "radix"])
    args = ap.parse_args(argv)
    host, port = args.host, args.port
    if args.conf:
        from dsort_tpu.config import load_conf_file

        conf = load_conf_file(args.conf)
        host = conf.get("SERVER_IP", host)
        port = int(conf.get("SERVER_PORT", port))
    SortWorker(host, port, dtype=args.dtype, backend=args.backend,
               kernel=args.kernel).serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
