// dsort coordinator — native TCP control plane (SURVEY.md §2.4 item 1).
//
// The DCN-path successor of the reference master's listener + worker_handler
// machinery (server.c:120-157, 297-477), speaking a length-prefixed framed
// protocol to Python/JAX worker shims instead of raw sentinel-terminated
// int32 pages (the reference's framing reserves key value -1 on the wire,
// server.c:405-406; length-prefixed frames reserve nothing).  Kept semantics,
// verified in SURVEY.md §5.3:
//   - passive in-band death detection (send/recv failure) — plus heartbeat
//     frames with a timeout monitor, fixing the reference's hang-blindness;
//   - whole-task retry on the first live worker (linear scan from 0), with
//     results pinned to the task id regardless of executor;
//   - clean job failure when no workers remain; the coordinator survives;
//   - unlike the reference (membership frozen at the initial accepts,
//     server.c:148-157), late/rejoining workers are accepted as new slots.
//
// Frame format (little-endian): u32 type | u32 task_id | u64 len | bytes.
// Types: 1 TASK (coord->worker), 2 RESULT (worker->coord),
//        3 HEARTBEAT (worker->coord), 4 SHUTDOWN (coord->worker).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "protocol.h"

namespace {

using dsort::FrameHeader;
using dsort::kHeartbeat;
using dsort::kResult;
using dsort::kShutdown;
using dsort::kTask;
using dsort::read_exact;
using dsort::send_all;

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// cv.wait_for via a system-clock deadline.  libstdc++ >= 10 lowers wait_for
// to pthread_cond_clockwait (CLOCK_MONOTONIC), which older ThreadSanitizer
// runtimes (gcc 10's libtsan among them) do not intercept — TSan then
// misses the wait's internal unlock and reports phantom "double lock of a
// mutex" plus cascading data races on everything mu_ guards, drowning real
// findings.  wait_until on system_clock takes the intercepted
// pthread_cond_timedwait path everywhere.  Trade-off: a wall-clock jump
// during the wait shifts the deadline; every use here is a liveness
// timeout where that is benign.
template <typename Pred>
bool wait_for_s(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
                double seconds, Pred pred) {
  return cv.wait_until(
      lk,
      std::chrono::system_clock::now() +
          std::chrono::duration_cast<std::chrono::system_clock::duration>(
              std::chrono::duration<double>(seconds)),
      pred);
}

struct Worker {
  int fd = -1;
  bool alive = false;
  bool hb_lapse_logged = false;  // one lapse event per hang, not per tick
  double last_hb = 0.0;
  // Per-socket send mutex: during reassignment a foreign task borrows a live
  // worker's socket; serialize like the reference's w_socket_mutexes
  // (server.c:23,321-346) — but only around sends; frames make interleaved
  // receives unambiguous, so no exchange-long lock is needed.
  std::unique_ptr<std::mutex> send_mu = std::make_unique<std::mutex>();
  std::thread reader;
};

enum class TaskState { kPending, kSent, kDone, kFailed };

struct Task {
  std::vector<uint8_t> data;
  std::vector<uint8_t> result;
  TaskState state = TaskState::kPending;
  int assigned = -1;
};

class Coordinator {
 public:
  Coordinator(uint16_t port, double hb_timeout)
      : hb_timeout_(hb_timeout) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(port);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 64) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { accept_loop(); });
    monitor_thread_ = std::thread([this] { monitor_loop(); });
  }

  ~Coordinator() { shutdown(); }

  bool ok() const { return listen_fd_ >= 0; }
  uint16_t port() const { return port_; }

  int wait_workers(int n, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    wait_for_s(cv_, lk, timeout_s,
               [&] { return total_connected_ >= n || stopping_; });
    return total_connected_;
  }

  int num_live() {
    std::lock_guard<std::mutex> lk(mu_);
    int c = 0;
    for (auto& w : workers_)
      if (w->alive) ++c;
    return c;
  }

  // Submit a task; dispatch happens inline (retrying across live workers).
  // Returns 0 on queued+sent, -1 when no live worker could take it.
  int submit(uint32_t task_id, const uint8_t* data, uint64_t len) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      Task& t = tasks_[task_id];
      t.data.assign(data, data + len);
      t.state = TaskState::kPending;
      t.assigned = -1;
    }
    return dispatch(task_id) ? 0 : -1;
  }

  // Block until the task completes; returns result length, -1 on job failure
  // (no live workers), -2 on timeout.  Result pinned to task_id.
  int64_t collect(uint32_t task_id, uint8_t* out, uint64_t cap, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    bool done = wait_for_s(cv_, lk, timeout_s, [&] {
      auto it = tasks_.find(task_id);
      return it != tasks_.end() && (it->second.state == TaskState::kDone ||
                                    it->second.state == TaskState::kFailed);
    });
    if (!done) return -2;
    Task& t = tasks_[task_id];
    if (t.state == TaskState::kFailed) return -1;
    uint64_t n = t.result.size();
    if (n > cap) return -3;
    std::memcpy(out, t.result.data(), n);
    return static_cast<int64_t>(n);
  }

  // Fault injection: hard-close a worker's socket (the kill -9 experiment).
  void kill_worker(int w) {
    std::lock_guard<std::mutex> lk(mu_);
    if (w >= 0 && w < static_cast<int>(workers_.size()) && workers_[w]->alive) {
      ::shutdown(workers_[w]->fd, SHUT_RDWR);
    }
  }

  void shutdown() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stopping_) return;
      stopping_ = true;
      for (auto& w : workers_) {
        if (w->alive) {
          FrameHeader h{kShutdown, 0, 0};
          std::lock_guard<std::mutex> slk(*w->send_mu);
          send_all(w->fd, &h, sizeof(h));
        }
        if (w->fd >= 0) ::shutdown(w->fd, SHUT_RDWR);
      }
      if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (monitor_thread_.joinable()) monitor_thread_.join();
    // Join readers WITHOUT holding mu_: a dying reader runs on_worker_down,
    // which needs mu_ — joining under the lock deadlocks against it.
    std::vector<std::thread> readers;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& w : workers_) {
        if (w->reader.joinable()) readers.push_back(std::move(w->reader));
      }
    }
    for (auto& t : readers) t.join();
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& w : workers_) {
        if (w->fd >= 0) ::close(w->fd);
        w->fd = -1;
      }
      if (listen_fd_ >= 0) ::close(listen_fd_);
      listen_fd_ = -1;
    }
  }

  int reassignments() {
    std::lock_guard<std::mutex> lk(mu_);
    return reassignments_;
  }

  // Drain buffered event lines into `buf` (newline-separated, NUL-free).
  // Copies only WHOLE lines that fit `cap`; drained lines are dropped,
  // lines that did not fit stay queued for the next drain.  Returns bytes
  // written.  Lines are "t=<secs> ev=<type> [w=<idx>] [task=<id>]" — one
  // compact line per coordinator state transition, parsed back into the
  // Python event journal by runtime/native.py.
  int64_t drain_events(char* buf, int64_t cap) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t off = 0;
    while (!events_.empty()) {
      const std::string& line = events_.front();
      int64_t need = static_cast<int64_t>(line.size()) + 1;
      if (off + need > cap) break;
      std::memcpy(buf + off, line.data(), line.size());
      off += static_cast<int64_t>(line.size());
      buf[off++] = '\n';
      events_.pop_front();
    }
    return off;
  }

 private:
  // Must be called with mu_ held.  Bounded queue: a consumer that never
  // drains cannot grow memory without bound (old events drop first).
  void log_event_locked(const char* type, int w, int64_t task) {
    char line[96];
    int n;
    if (w >= 0 && task >= 0) {
      n = std::snprintf(line, sizeof(line), "t=%.6f ev=%s w=%d task=%lld",
                        now_s(), type, w, static_cast<long long>(task));
    } else if (w >= 0) {
      n = std::snprintf(line, sizeof(line), "t=%.6f ev=%s w=%d", now_s(),
                        type, w);
    } else {
      n = std::snprintf(line, sizeof(line), "t=%.6f ev=%s task=%lld",
                        now_s(), type, static_cast<long long>(task));
    }
    if (n <= 0) return;
    if (events_.size() >= 4096) events_.pop_front();
    events_.emplace_back(line, static_cast<size_t>(n));
  }
  void accept_loop() {
    while (true) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;  // listener closed
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      int idx;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stopping_) {
          ::close(fd);
          return;
        }
        workers_.push_back(std::make_unique<Worker>());
        idx = static_cast<int>(workers_.size()) - 1;
        Worker& w = *workers_[idx];
        w.fd = fd;
        w.alive = true;
        w.last_hb = now_s();
        ++total_connected_;
        log_event_locked("worker_join", idx, -1);
        w.reader = std::thread([this, idx] { reader_loop(idx); });
      }
      cv_.notify_all();
    }
  }

  void reader_loop(int widx) {
    int fd;
    {
      std::lock_guard<std::mutex> lk(mu_);
      fd = workers_[widx]->fd;
    }
    while (true) {
      FrameHeader h;
      if (fd < 0 || !read_exact(fd, &h, sizeof(h))) break;
      if (h.type == kHeartbeat) {
        std::lock_guard<std::mutex> lk(mu_);
        workers_[widx]->last_hb = now_s();
        continue;
      }
      if (h.type == kResult) {
        std::vector<uint8_t> payload(h.len);
        if (h.len > 0 && !read_exact(fd, payload.data(), h.len)) break;
        {
          std::lock_guard<std::mutex> lk(mu_);
          workers_[widx]->last_hb = now_s();
          auto it = tasks_.find(h.task_id);
          if (it != tasks_.end() && it->second.state == TaskState::kSent) {
            it->second.result = std::move(payload);
            it->second.state = TaskState::kDone;
            log_event_locked("task_done", widx, h.task_id);
          }
        }
        cv_.notify_all();
        continue;
      }
      break;  // unknown frame: treat as protocol death
    }
    on_worker_down(widx);
  }

  // Death handling: mark dead and retry this worker's in-flight tasks whole
  // on the first live worker (server.c:367-401 semantics).
  void on_worker_down(int widx) {
    std::vector<uint32_t> orphans;
    {
      std::lock_guard<std::mutex> lk(mu_);
      Worker& w = *workers_[widx];
      if (!w.alive) return;
      w.alive = false;
      log_event_locked("worker_dead", widx, -1);
      for (auto& [id, t] : tasks_) {
        if (t.state == TaskState::kSent && t.assigned == widx) {
          t.state = TaskState::kPending;
          ++reassignments_;  // recv-path detection (server.c:421-448)
          log_event_locked("reassign", widx, id);
          orphans.push_back(id);
        }
      }
    }
    cv_.notify_all();
    for (uint32_t id : orphans) {
      std::this_thread::sleep_for(std::chrono::milliseconds(100));  // server.c:391
      dispatch(id);
    }
  }

  bool dispatch(uint32_t task_id) {
    bool first_try = true;
    while (true) {
      int target = -1;
      Worker* w = nullptr;  // Worker objects are unique_ptr-held: stable
                            // across workers_ growth, safe to use unlocked.
      FrameHeader h{kTask, task_id, 0};
      std::vector<uint8_t>* data_ptr = nullptr;
      {
        std::lock_guard<std::mutex> lk(mu_);
        int n = static_cast<int>(workers_.size());
        // Prefer the task-affine worker (reference: chunk i <-> worker i,
        // server.c:231-257); otherwise linear-scan first live
        // (server.c:368-384).
        int affine = n > 0 ? static_cast<int>(task_id) % n : -1;
        if (first_try && affine >= 0 && workers_[affine]->alive) {
          target = affine;
        } else {
          for (int i = 0; i < n; ++i) {
            if (workers_[i]->alive) {
              target = i;
              break;
            }
          }
        }
        auto it = tasks_.find(task_id);
        if (it == tasks_.end()) return false;
        if (target < 0) {
          it->second.state = TaskState::kFailed;  // clean job failure
          log_event_locked("job_failed", -1, task_id);
          cv_.notify_all();
          return false;
        }
        w = workers_[target].get();
        it->second.assigned = target;
        it->second.state = TaskState::kSent;
        log_event_locked("attempt_start", target, task_id);
        data_ptr = &it->second.data;
        h.len = data_ptr->size();
      }
      first_try = false;
      bool sent;
      {
        std::lock_guard<std::mutex> slk(*w->send_mu);
        sent = send_all(w->fd, &h, sizeof(h)) &&
               (h.len == 0 || send_all(w->fd, data_ptr->data(), h.len));
      }
      if (sent) return true;
      // Send failed: in-band death detection (server.c:358); mark + retry.
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (workers_[target]->alive) {
          workers_[target]->alive = false;
          log_event_locked("worker_dead", target, -1);
        }
        auto it = tasks_.find(task_id);
        it->second.state = TaskState::kPending;
        ++reassignments_;
        log_event_locked("reassign", target, task_id);
      }
      cv_.notify_all();
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
  }

  void monitor_loop() {
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (wait_for_s(cv_, lk, 0.2, [&] { return stopping_; })) return;
        double t = now_s();
        for (int i = 0; i < static_cast<int>(workers_.size()); ++i) {
          Worker& w = *workers_[i];
          if (w.alive && hb_timeout_ > 0 && t - w.last_hb > hb_timeout_ &&
              !w.hb_lapse_logged) {
            // Hang-blindness fix: no heartbeat -> force the socket closed;
            // the reader thread then runs the normal death path.  The flag
            // keeps a delayed reader from producing one lapse event (and
            // one extra shutdown call) per 200 ms monitor tick.
            w.hb_lapse_logged = true;
            log_event_locked("heartbeat_lapse", i, -1);
            ::shutdown(w.fd, SHUT_RDWR);
          }
        }
      }
    }
  }

  int listen_fd_ = -1;
  uint16_t port_ = 0;
  double hb_timeout_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<std::unique_ptr<Worker>> workers_;
  std::map<uint32_t, Task> tasks_;
  int total_connected_ = 0;
  int reassignments_ = 0;
  std::deque<std::string> events_;
  bool stopping_ = false;
  std::thread accept_thread_;
  std::thread monitor_thread_;
};

}  // namespace

extern "C" {

void* dsort_coord_create(uint16_t port, double hb_timeout) {
  auto* c = new Coordinator(port, hb_timeout);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

int32_t dsort_coord_port(void* c) {
  return static_cast<Coordinator*>(c)->port();
}

int32_t dsort_coord_wait_workers(void* c, int32_t n, double timeout_s) {
  return static_cast<Coordinator*>(c)->wait_workers(n, timeout_s);
}

int32_t dsort_coord_num_live(void* c) {
  return static_cast<Coordinator*>(c)->num_live();
}

int32_t dsort_coord_submit(void* c, uint32_t task_id, const uint8_t* data,
                           uint64_t len) {
  return static_cast<Coordinator*>(c)->submit(task_id, data, len);
}

int64_t dsort_coord_collect(void* c, uint32_t task_id, uint8_t* out,
                            uint64_t cap, double timeout_s) {
  return static_cast<Coordinator*>(c)->collect(task_id, out, cap, timeout_s);
}

void dsort_coord_kill_worker(void* c, int32_t w) {
  static_cast<Coordinator*>(c)->kill_worker(w);
}

int32_t dsort_coord_reassignments(void* c) {
  return static_cast<Coordinator*>(c)->reassignments();
}

int64_t dsort_coord_drain_events(void* c, char* buf, int64_t cap) {
  return static_cast<Coordinator*>(c)->drain_events(buf, cap);
}

void dsort_coord_shutdown(void* c) {
  static_cast<Coordinator*>(c)->shutdown();
}

void dsort_coord_destroy(void* c) {
  static_cast<Coordinator*>(c)->shutdown();
  delete static_cast<Coordinator*>(c);
}

}  // extern "C"
