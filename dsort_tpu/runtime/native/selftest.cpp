// Native self-test for the dsort coordinator + merge + worker table.
//
// Exercises the full coordinator protocol in ONE process (in-process fake
// workers over real sockets): healthy jobs, worker kill mid-cluster with
// reassignment, all-dead clean failure, and the k-way merge / worker-table
// primitives.  Built plain or with -fsanitize=thread (`make tsan-selftest`)
// so the runtime's locking is validated under TSan — the reference hand-
// manages its races and was never sanitized (SURVEY.md §5.2).
//
// Exit code 0 = all checks passed.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include "protocol.h"

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

extern "C" {
void* dsort_coord_create(uint16_t port, double hb_timeout);
int32_t dsort_coord_port(void* c);
int32_t dsort_coord_wait_workers(void* c, int32_t n, double timeout_s);
int32_t dsort_coord_num_live(void* c);
int32_t dsort_coord_submit(void* c, uint32_t task_id, const uint8_t* data,
                           uint64_t len);
int64_t dsort_coord_collect(void* c, uint32_t task_id, uint8_t* out,
                            uint64_t cap, double timeout_s);
void dsort_coord_kill_worker(void* c, int32_t w);
int32_t dsort_coord_reassignments(void* c);
void dsort_coord_destroy(void* c);

void dsort_kway_merge_i32(const int32_t** runs, const int64_t* lens,
                          int32_t nruns, int32_t* out);
void dsort_kway_merge_par_i32(const int32_t** runs, const int64_t* lens,
                              int32_t nruns, int32_t* out, int32_t nthreads);
void* dsort_table_create(int32_t n, double heartbeat_timeout_s);
void dsort_table_destroy(void* t);
void dsort_table_mark_dead(void* t, int32_t w);
int32_t dsort_table_first_live(void* t, int32_t exclude);
int32_t dsort_table_live_count(void* t);
}

namespace {

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,     \
                   __LINE__, #cond);                                    \
      std::exit(1);                                                     \
    }                                                                   \
  } while (0)

using Hdr = dsort::FrameHeader;
using dsort::read_exact;
using dsort::send_all;

// A fake worker: connects, sorts int32 task payloads, replies after
// delay_ms (a nonzero delay keeps tasks in flight long enough for kill
// tests to exercise the reassignment path deterministically).
void fake_worker(uint16_t port, std::atomic<bool>* stop, int delay_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  ::inet_pton(AF_INET, "127.0.0.1", &a.sin_addr);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) != 0) {
    ::close(fd);
    return;
  }
  while (!stop->load()) {
    Hdr h;
    if (!read_exact(fd, &h, sizeof(h))) break;
    if (h.type == dsort::kShutdown) break;
    if (h.type != dsort::kTask) continue;
    std::vector<uint8_t> buf(h.len);
    if (h.len && !read_exact(fd, buf.data(), h.len)) break;
    auto* ints = reinterpret_cast<int32_t*>(buf.data());
    std::sort(ints, ints + h.len / 4);
    if (delay_ms > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
    Hdr r{dsort::kResult, h.task_id, h.len};
    if (!send_all(fd, &r, sizeof(r)) || !send_all(fd, buf.data(), h.len)) break;
  }
  ::close(fd);
}

void test_merge_and_table() {
  std::mt19937 rng(1);
  std::vector<std::vector<int32_t>> runs(5);
  std::vector<const int32_t*> ptrs;
  std::vector<int64_t> lens;
  std::vector<int32_t> all;
  for (auto& r : runs) {
    size_t n = rng() % 1000;
    r.resize(n);
    for (auto& v : r) v = static_cast<int32_t>(rng());
    std::sort(r.begin(), r.end());
    all.insert(all.end(), r.begin(), r.end());
    ptrs.push_back(r.data());
    lens.push_back(static_cast<int64_t>(n));
  }
  std::vector<int32_t> out(all.size());
  dsort_kway_merge_i32(ptrs.data(), lens.data(), 5, out.data());
  std::sort(all.begin(), all.end());
  CHECK(out == all);

  // Parallel range-partitioned merge, big enough to cross its 2^20 serial
  // cutoff — under the TSan build this also proves the threading is clean.
  std::vector<std::vector<int32_t>> big(4);
  std::vector<const int32_t*> bptrs;
  std::vector<int64_t> blens;
  std::vector<int32_t> ball;
  for (auto& r : big) {
    r.resize(400000);
    for (auto& v : r) v = static_cast<int32_t>(rng() % 1000);  // heavy dups
    std::sort(r.begin(), r.end());
    ball.insert(ball.end(), r.begin(), r.end());
    bptrs.push_back(r.data());
    blens.push_back(static_cast<int64_t>(r.size()));
  }
  std::vector<int32_t> bout(ball.size());
  dsort_kway_merge_par_i32(bptrs.data(), blens.data(), 4, bout.data(), 6);
  std::sort(ball.begin(), ball.end());
  CHECK(bout == ball);

  void* t = dsort_table_create(4, 10.0);
  CHECK(dsort_table_first_live(t, -1) == 0);
  dsort_table_mark_dead(t, 0);
  dsort_table_mark_dead(t, 2);
  CHECK(dsort_table_first_live(t, -1) == 1);
  CHECK(dsort_table_first_live(t, 1) == 3);
  CHECK(dsort_table_live_count(t) == 2);
  dsort_table_destroy(t);
  std::printf("merge+table ok\n");
}

void test_coordinator() {
  // hb_timeout=0 disables the heartbeat monitor: fake workers send no
  // heartbeats, and this test covers the exchange paths, not liveness
  // timing (the Python cluster tests cover heartbeats with real shims).
  void* c = dsort_coord_create(0, 0.0);
  CHECK(c != nullptr);
  uint16_t port = static_cast<uint16_t>(dsort_coord_port(c));
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;
  for (int i = 0; i < 4; ++i)
    workers.emplace_back(fake_worker, port, &stop, /*delay_ms=*/150);
  CHECK(dsort_coord_wait_workers(c, 4, 10.0) >= 4);

  // Concurrent submit/collect from multiple threads.
  std::mt19937 rng(7);
  std::vector<std::vector<int32_t>> shards(8);
  for (uint32_t i = 0; i < 8; ++i) {
    shards[i].resize(2000 + (rng() % 100));
    for (auto& v : shards[i]) v = static_cast<int32_t>(rng());
    CHECK(dsort_coord_submit(
              c, i, reinterpret_cast<const uint8_t*>(shards[i].data()),
              shards[i].size() * 4) == 0);
  }
  // Kill worker 2 while its affine tasks (ids 2 and 6) are still in flight
  // (workers reply after 150 ms) — forces the reassignment path.
  dsort_coord_kill_worker(c, 2);
  std::vector<std::thread> collectors;
  std::atomic<int> ok{0};
  for (uint32_t i = 0; i < 8; ++i) {
    collectors.emplace_back([&, i] {
      std::vector<int32_t> out(shards[i].size());
      int64_t n = dsort_coord_collect(
          c, i, reinterpret_cast<uint8_t*>(out.data()), out.size() * 4, 30.0);
      if (n != static_cast<int64_t>(out.size() * 4)) return;
      auto expect = shards[i];
      std::sort(expect.begin(), expect.end());
      if (out == expect) ok.fetch_add(1);
    });
  }
  for (auto& t : collectors) t.join();
  CHECK(ok.load() == 8);
  CHECK(dsort_coord_num_live(c) == 3);
  // The dead worker's affine tasks were re-dispatched: either the send into
  // its closed socket failed (send-path detection -> reassignments_++) or
  // its reader died with tasks in flight (recv-path detection).
  CHECK(dsort_coord_reassignments(c) >= 1);

  stop.store(true);
  dsort_coord_destroy(c);  // sends shutdown; workers unblock and exit
  for (auto& t : workers) t.join();
  std::printf("coordinator ok\n");
}

void test_all_dead() {
  void* c = dsort_coord_create(0, 2.0);
  uint16_t port = static_cast<uint16_t>(dsort_coord_port(c));
  std::atomic<bool> stop{false};
  std::thread w(fake_worker, port, &stop, /*delay_ms=*/0);
  CHECK(dsort_coord_wait_workers(c, 1, 10.0) >= 1);
  dsort_coord_kill_worker(c, 0);
  w.join();
  // Give the reader thread a moment to run the death path.
  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  int32_t v = 42;
  int rc = dsort_coord_submit(c, 0, reinterpret_cast<uint8_t*>(&v), 4);
  if (rc == 0) {
    // Submit raced the death detection; the task must FAIL cleanly (-1),
    // not time out (-2) — a hang here would be a regression.
    uint8_t out[4];
    CHECK(dsort_coord_collect(c, 0, out, 4, 20.0) == -1);
  }
  dsort_coord_destroy(c);
  std::printf("all-dead ok\n");
}

}  // namespace

int main() {
  test_merge_and_table();
  test_coordinator();
  test_all_dead();
  std::printf("SELFTEST PASS\n");
  return 0;
}
