// Shared wire protocol for the dsort coordinator and its clients.
//
// Length-prefixed frames (little-endian): u32 type | u32 task_id | u64 len |
// payload bytes.  Replaces the reference's raw int32 pages terminated by an
// in-band -1 sentinel (server.c:405-406, client.c:113), which reserves a key
// value; frames reserve nothing.  The Python worker shim
// (dsort_tpu/runtime/worker.py) packs the same header with struct "<IIQ".

#ifndef DSORT_PROTOCOL_H_
#define DSORT_PROTOCOL_H_

#include <sys/socket.h>

#include <cstdint>

namespace dsort {

constexpr uint32_t kTask = 1;       // coord -> worker: sort this payload
constexpr uint32_t kResult = 2;     // worker -> coord: sorted payload
constexpr uint32_t kHeartbeat = 3;  // worker -> coord: liveness
constexpr uint32_t kShutdown = 4;   // coord -> worker: exit cleanly

struct FrameHeader {
  uint32_t type;
  uint32_t task_id;
  uint64_t len;
} __attribute__((packed));

inline bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<unsigned char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// MSG_NOSIGNAL: a dead peer surfaces as an error return, never SIGPIPE —
// the property the reference gets via signal(SIGPIPE, SIG_IGN)
// (server.c:108-116).
inline bool send_all(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const unsigned char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace dsort

#endif  // DSORT_PROTOCOL_H_
