// textio — native ASCII integer ingest/egress for dsort_tpu.
//
// Native parity with the reference's file IO (SURVEY.md §2.1): the reference
// ingests with a two-pass fscanf loop (count, rewind, fill — server.c:171-182)
// and egresses one fprintf per int (server.c:517-519), all in C.  These are
// the framework's equivalents, operating on whole memory buffers so Python
// does one read()/write() syscall per file and the hot loops are native:
//
//  - dsort_count_ints: pass 1 — token count for exact output allocation;
//  - dsort_parse_{i32,i64,u32,u64}: pass 2 — std::from_chars per token;
//  - dsort_format_{i32,i64,u32,u64}: std::to_chars, one int per line
//    (byte-compatible with the reference's output.txt format).
//
// Tokens are separated by arbitrary ASCII whitespace; '+'/'-' signs follow
// std::from_chars semantics (leading '-' only; '+' is rejected like numpy's
// loadtxt int path would parse it — see PARSE_BAD_CHAR below).  All errors
// are returned as negative codes (no exceptions across the C ABI).

#include <cctype>
#include <charconv>
#include <cstdint>

namespace {

constexpr int64_t PARSE_BAD_CHAR = -1;   // token is not a valid integer
constexpr int64_t PARSE_RANGE = -2;      // token out of dtype range
constexpr int64_t PARSE_OVERFLOW_CAP = -3;  // more tokens than `cap`

inline bool is_space(char c) {
  return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\v' ||
         c == '\f';
}

template <typename T>
int64_t parse_ints(const char* buf, int64_t len, T* out, int64_t cap) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  while (true) {
    while (p < end && is_space(*p)) ++p;
    if (p >= end) return n;
    if (n >= cap) return PARSE_OVERFLOW_CAP;
    T value;
    auto res = std::from_chars(p, end, value);
    if (res.ec == std::errc::result_out_of_range) return PARSE_RANGE;
    if (res.ec != std::errc() || (res.ptr < end && !is_space(*res.ptr)))
      return PARSE_BAD_CHAR;
    out[n++] = value;
    p = res.ptr;
  }
}

template <typename T>
int64_t format_ints(const T* data, int64_t n, char* out, int64_t cap) {
  char* p = out;
  char* end = out + cap;
  for (int64_t i = 0; i < n; ++i) {
    auto res = std::to_chars(p, end, data[i]);
    if (res.ec != std::errc() || res.ptr >= end) return -1;
    p = res.ptr;
    *p++ = '\n';
  }
  return p - out;
}

}  // namespace

extern "C" {

// Count integer tokens in `buf`; returns a negative PARSE_* code on a
// malformed token so the caller can fall back before allocating output.
int64_t dsort_count_ints(const char* buf, int64_t len) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  while (true) {
    while (p < end && is_space(*p)) ++p;
    if (p >= end) return n;
    int64_t value;  // widest signed probe; range is re-checked per dtype later
    auto res = std::from_chars(p, end, value);
    if (res.ec == std::errc::result_out_of_range) {
      // Could still be a valid uint64 above INT64_MAX; probe unsigned too.
      uint64_t uvalue;
      res = std::from_chars(p, end, uvalue);
      if (res.ec != std::errc()) return PARSE_RANGE;
    } else if (res.ec != std::errc()) {
      return PARSE_BAD_CHAR;
    }
    if (res.ptr < end && !is_space(*res.ptr)) return PARSE_BAD_CHAR;
    ++n;
    p = res.ptr;
  }
}

int64_t dsort_parse_i32(const char* buf, int64_t len, int32_t* out, int64_t cap) {
  return parse_ints<int32_t>(buf, len, out, cap);
}
int64_t dsort_parse_i64(const char* buf, int64_t len, int64_t* out, int64_t cap) {
  return parse_ints<int64_t>(buf, len, out, cap);
}
int64_t dsort_parse_u32(const char* buf, int64_t len, uint32_t* out, int64_t cap) {
  return parse_ints<uint32_t>(buf, len, out, cap);
}
int64_t dsort_parse_u64(const char* buf, int64_t len, uint64_t* out, int64_t cap) {
  return parse_ints<uint64_t>(buf, len, out, cap);
}

int64_t dsort_format_i32(const int32_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<int32_t>(data, n, out, cap);
}
int64_t dsort_format_i64(const int64_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<int64_t>(data, n, out, cap);
}
int64_t dsort_format_u32(const uint32_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<uint32_t>(data, n, out, cap);
}
int64_t dsort_format_u64(const uint64_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<uint64_t>(data, n, out, cap);
}

}  // extern "C"
