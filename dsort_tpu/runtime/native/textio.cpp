// textio — native ASCII integer ingest/egress for dsort_tpu.
//
// Native parity with the reference's file IO (SURVEY.md §2.1): the reference
// ingests with a two-pass fscanf loop (count, rewind, fill — server.c:171-182)
// and egresses one fprintf per int (server.c:517-519), all in C.  These are
// the framework's equivalents, operating on whole memory buffers so Python
// does one read()/write() syscall per file and the hot loops are native:
//
//  - dsort_count_ints: pass 1 — token count for exact output allocation;
//  - dsort_parse_{i32,i64,u32,u64}: pass 2 — std::from_chars per token;
//  - dsort_format_{i32,i64,u32,u64}: std::to_chars, one int per line
//    (byte-compatible with the reference's output.txt format).
//
// Tokens are separated by arbitrary ASCII whitespace; '+'/'-' signs follow
// std::from_chars semantics (leading '-' only; '+' is rejected like numpy's
// loadtxt int path would parse it — see PARSE_BAD_CHAR below).  All errors
// are returned as negative codes (no exceptions across the C ABI).

#include <cctype>
#include <charconv>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int64_t PARSE_BAD_CHAR = -1;   // token is not a valid integer
constexpr int64_t PARSE_RANGE = -2;      // token out of dtype range
constexpr int64_t PARSE_OVERFLOW_CAP = -3;  // more tokens than `cap`

inline bool is_space(char c) {
  return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\v' ||
         c == '\f';
}

// The one tokenizer all int-parsing paths share (serial parse, MT count, MT
// parse), so the grammar can never diverge between passes.  ``f(value, n)``
// returns 0 to continue or a negative PARSE_* code to abort.
template <typename T, typename F>
int64_t for_each_int(const char* buf, int64_t len, F&& f) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  while (true) {
    while (p < end && is_space(*p)) ++p;
    if (p >= end) return n;
    T value;
    auto res = std::from_chars(p, end, value);
    if (res.ec == std::errc::result_out_of_range) return PARSE_RANGE;
    if (res.ec != std::errc() || (res.ptr < end && !is_space(*res.ptr)))
      return PARSE_BAD_CHAR;
    int64_t rc = f(value, n);
    if (rc < 0) return rc;
    ++n;
    p = res.ptr;
  }
}

template <typename T>
int64_t parse_ints(const char* buf, int64_t len, T* out, int64_t cap) {
  return for_each_int<T>(buf, len, [&](T value, int64_t n) -> int64_t {
    if (n >= cap) return PARSE_OVERFLOW_CAP;
    out[n] = value;
    return 0;
  });
}

template <typename T>
int64_t count_tokens(const char* buf, int64_t len) {
  return for_each_int<T>(buf, len, [](T, int64_t) -> int64_t { return 0; });
}

template <typename T>
int64_t format_ints(const T* data, int64_t n, char* out, int64_t cap) {
  char* p = out;
  char* end = out + cap;
  for (int64_t i = 0; i < n; ++i) {
    auto res = std::to_chars(p, end, data[i]);
    if (res.ec != std::errc() || res.ptr >= end) return -1;
    p = res.ptr;
    *p++ = '\n';
  }
  return p - out;
}

// Split [0, len) into at most `nthreads` ranges whose boundaries fall on
// whitespace, so no token straddles two ranges.  Returns the range ends.
std::vector<int64_t> split_at_whitespace(const char* buf, int64_t len,
                                         int32_t nthreads) {
  std::vector<int64_t> ends;
  int64_t step = len / nthreads;
  int64_t prev = 0;
  for (int32_t t = 0; t + 1 < nthreads; ++t) {
    int64_t cut = prev + step;
    if (cut >= len) break;
    while (cut < len && !is_space(buf[cut])) ++cut;  // finish current token
    if (cut > prev) ends.push_back(cut);
    prev = cut;
  }
  ends.push_back(len);
  return ends;
}

// Parallel parse: a count pass sizes each range's output offset, then every
// range parses directly into its slice of `out`.  Both passes fan out over
// `nthreads` std::threads; any per-range error code wins (first range order).
// On PARSE_OVERFLOW_CAP, `*needed` (if non-null) receives the exact token
// count so the caller can allocate once and retry without re-counting.
template <typename T>
int64_t parse_ints_mt(const char* buf, int64_t len, T* out, int64_t cap,
                      int32_t nthreads, int64_t* needed) {
  if (nthreads <= 1 || len < (1 << 20)) return parse_ints<T>(buf, len, out, cap);
  std::vector<int64_t> ends = split_at_whitespace(buf, len, nthreads);
  int32_t nr = ends.size();
  std::vector<int64_t> counts(nr, 0);
  {
    std::vector<std::thread> ths;
    int64_t start = 0;
    for (int32_t t = 0; t < nr; ++t) {
      int64_t s = start, e = ends[t];
      start = e;
      ths.emplace_back([&, t, s, e] { counts[t] = count_tokens<T>(buf + s, e - s); });
    }
    for (auto& th : ths) th.join();
  }
  int64_t total = 0;
  for (int32_t t = 0; t < nr; ++t) {
    if (counts[t] < 0) return counts[t];
    total += counts[t];
  }
  if (total > cap) {
    if (needed) *needed = total;
    return PARSE_OVERFLOW_CAP;
  }
  std::vector<int64_t> results(nr, 0);
  {
    std::vector<std::thread> ths;
    int64_t start = 0, off = 0;
    for (int32_t t = 0; t < nr; ++t) {
      int64_t s = start, e = ends[t], o = off;
      start = e;
      off += counts[t];
      ths.emplace_back([&, t, s, e, o] {
        results[t] = parse_ints<T>(buf + s, e - s, out + o, counts[t]);
      });
    }
    for (auto& th : ths) th.join();
  }
  for (int32_t t = 0; t < nr; ++t) {
    if (results[t] < 0) return results[t];
  }
  return total;
}

// Parallel format: each range formats into out at a precomputed worst-case
// offset stride, then ranges are compacted left with memmove (cheap vs the
// to_chars work).  Returns total bytes or -1 if `cap` is too small.
template <typename T>
int64_t format_ints_mt(const T* data, int64_t n, char* out, int64_t cap,
                       int32_t max_width, int32_t nthreads) {
  if (nthreads <= 1 || n < (1 << 18)) return format_ints<T>(data, n, out, cap);
  if (cap < n * (int64_t)max_width + 1) return -1;
  int32_t nr = nthreads;
  int64_t per = (n + nr - 1) / nr;
  std::vector<int64_t> sizes(nr, 0);
  {
    std::vector<std::thread> ths;
    for (int32_t t = 0; t < nr; ++t) {
      int64_t s = t * per, e = std::min<int64_t>(n, s + per);
      if (s >= e) break;
      // A range's slot is exactly (e-s)*max_width bytes: if a caller ever
      // understates max_width, the range reports -1 instead of silently
      // writing the first byte of its neighbor's slot (a data race).  The
      // final range gets the global +1 slack byte of `cap`.
      int64_t slot = (e - s) * (int64_t)max_width;
      if (e == n) slot = cap - s * (int64_t)max_width;
      ths.emplace_back([&, t, s, e, slot] {
        sizes[t] = format_ints<T>(data + s, e - s, out + s * max_width, slot);
      });
    }
    for (auto& th : ths) th.join();
  }
  int64_t total = 0;
  for (int32_t t = 0; t < nr; ++t) {
    if (sizes[t] < 0) return -1;
    if (sizes[t] == 0) continue;
    int64_t src = t * per * max_width;
    if (src != total) std::memmove(out + total, out + src, sizes[t]);
    total += sizes[t];
  }
  return total;
}

}  // namespace

extern "C" {

// Count integer tokens in `buf`; returns a negative PARSE_* code on a
// malformed token so the caller can fall back before allocating output.
int64_t dsort_count_ints(const char* buf, int64_t len) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  while (true) {
    while (p < end && is_space(*p)) ++p;
    if (p >= end) return n;
    int64_t value;  // widest signed probe; range is re-checked per dtype later
    auto res = std::from_chars(p, end, value);
    if (res.ec == std::errc::result_out_of_range) {
      // Could still be a valid uint64 above INT64_MAX; probe unsigned too.
      uint64_t uvalue;
      res = std::from_chars(p, end, uvalue);
      if (res.ec != std::errc()) return PARSE_RANGE;
    } else if (res.ec != std::errc()) {
      return PARSE_BAD_CHAR;
    }
    if (res.ptr < end && !is_space(*res.ptr)) return PARSE_BAD_CHAR;
    ++n;
    p = res.ptr;
  }
}

int64_t dsort_parse_i32(const char* buf, int64_t len, int32_t* out, int64_t cap) {
  return parse_ints<int32_t>(buf, len, out, cap);
}
int64_t dsort_parse_i64(const char* buf, int64_t len, int64_t* out, int64_t cap) {
  return parse_ints<int64_t>(buf, len, out, cap);
}
int64_t dsort_parse_u32(const char* buf, int64_t len, uint32_t* out, int64_t cap) {
  return parse_ints<uint32_t>(buf, len, out, cap);
}
int64_t dsort_parse_u64(const char* buf, int64_t len, uint64_t* out, int64_t cap) {
  return parse_ints<uint64_t>(buf, len, out, cap);
}

int64_t dsort_format_i32(const int32_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<int32_t>(data, n, out, cap);
}
int64_t dsort_format_i64(const int64_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<int64_t>(data, n, out, cap);
}
int64_t dsort_format_u32(const uint32_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<uint32_t>(data, n, out, cap);
}
int64_t dsort_format_u64(const uint64_t* data, int64_t n, char* out, int64_t cap) {
  return format_ints<uint64_t>(data, n, out, cap);
}

// Multi-threaded variants (small inputs fall through to the serial paths).
// `needed` (nullable) receives the exact token count on PARSE_OVERFLOW_CAP.
int64_t dsort_parse_mt_i32(const char* buf, int64_t len, int32_t* out,
                           int64_t cap, int32_t nthreads, int64_t* needed) {
  return parse_ints_mt<int32_t>(buf, len, out, cap, nthreads, needed);
}
int64_t dsort_parse_mt_i64(const char* buf, int64_t len, int64_t* out,
                           int64_t cap, int32_t nthreads, int64_t* needed) {
  return parse_ints_mt<int64_t>(buf, len, out, cap, nthreads, needed);
}
int64_t dsort_parse_mt_u32(const char* buf, int64_t len, uint32_t* out,
                           int64_t cap, int32_t nthreads, int64_t* needed) {
  return parse_ints_mt<uint32_t>(buf, len, out, cap, nthreads, needed);
}
int64_t dsort_parse_mt_u64(const char* buf, int64_t len, uint64_t* out,
                           int64_t cap, int32_t nthreads, int64_t* needed) {
  return parse_ints_mt<uint64_t>(buf, len, out, cap, nthreads, needed);
}

int64_t dsort_format_mt_i32(const int32_t* data, int64_t n, char* out,
                            int64_t cap, int32_t max_width, int32_t nthreads) {
  return format_ints_mt<int32_t>(data, n, out, cap, max_width, nthreads);
}
int64_t dsort_format_mt_i64(const int64_t* data, int64_t n, char* out,
                            int64_t cap, int32_t max_width, int32_t nthreads) {
  return format_ints_mt<int64_t>(data, n, out, cap, max_width, nthreads);
}
int64_t dsort_format_mt_u32(const uint32_t* data, int64_t n, char* out,
                            int64_t cap, int32_t max_width, int32_t nthreads) {
  return format_ints_mt<uint32_t>(data, n, out, cap, max_width, nthreads);
}
int64_t dsort_format_mt_u64(const uint64_t* data, int64_t n, char* out,
                            int64_t cap, int32_t max_width, int32_t nthreads) {
  return format_ints_mt<uint64_t>(data, n, out, cap, max_width, nthreads);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Validation primitives (the valsort role of the TeraSort tool suite):
// a permutation-invariant multiset checksum and a big-endian key order check,
// both chunk-callable so Python can stream arbitrarily large files.
// ---------------------------------------------------------------------------

extern "C" {

// Sum (mod 2^64) of FNV-1a 64-bit hashes of each rec_bytes-sized record.
// Addition is commutative, so equal multisets of records give equal sums
// regardless of order — comparing input and output proves permutation.
uint64_t dsort_fnv_multiset(const uint8_t* buf, int64_t nrec,
                            int32_t rec_bytes) {
  uint64_t sum = 0;
  for (int64_t i = 0; i < nrec; ++i) {
    const uint8_t* r = buf + i * rec_bytes;
    uint64_t h = 1469598103934665603ull;  // FNV offset basis
    for (int32_t b = 0; b < rec_bytes; ++b) {
      h ^= r[b];
      h *= 1099511628211ull;  // FNV prime
    }
    sum += h;
  }
  return sum;
}

// First index i (1-based within this chunk) where record i's key compares
// below record i-1's key as a big-endian byte string (memcmp on the first
// key_bytes of each record, the TeraSort order), or -1 if nondecreasing.
int64_t dsort_check_order_be(const uint8_t* buf, int64_t nrec,
                             int32_t rec_bytes, int32_t key_bytes) {
  for (int64_t i = 1; i < nrec; ++i) {
    if (std::memcmp(buf + i * rec_bytes, buf + (i - 1) * rec_bytes,
                    key_bytes) < 0)
      return i;
  }
  return -1;
}

}  // extern "C"
