// dsort_native — native runtime core for dsort_tpu.
//
// Native parity with the reference's C master (SURVEY.md §2.4): the
// reference implements its k-way merge (server.c:481-524, an O(N*k) linear
// min-scan) and its scheduler/liveness state machine (server.c:19,297-477)
// in C.  This library provides the TPU framework's equivalents:
//
//  - an O(N log k) binary-heap k-way merge over sorted runs (key-only for
//    int32/int64/uint64, and key+fixed-width-payload for TeraSort records),
//    used by the host data plane for egress assembly;
//  - a thread-safe worker liveness table with heartbeat timestamps and
//    linear-scan first-live lookup — the reassign-on-failure state machine
//    with the reference's verified semantics (mark-dead, first-live scan,
//    per-job optimistic revival) minus its unlocked is_alive[] race
//    (SURVEY.md §5.2).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// K-way merge: binary min-heap of run heads.
// ---------------------------------------------------------------------------

template <typename K>
struct HeapNode {
  K key;
  int32_t run;
};

template <typename K>
class RunHeap {
 public:
  explicit RunHeap(int32_t capacity) { nodes_.reserve(capacity); }

  void push(K key, int32_t run) {
    nodes_.push_back({key, run});
    size_t i = nodes_.size() - 1;
    while (i > 0) {
      size_t parent = (i - 1) / 2;
      if (nodes_[parent].key <= nodes_[i].key) break;
      std::swap(nodes_[parent], nodes_[i]);
      i = parent;
    }
  }

  HeapNode<K> pop() {
    HeapNode<K> top = nodes_[0];
    nodes_[0] = nodes_.back();
    nodes_.pop_back();
    size_t i = 0, n = nodes_.size();
    while (true) {
      size_t l = 2 * i + 1, r = 2 * i + 2, m = i;
      if (l < n && nodes_[l].key < nodes_[m].key) m = l;
      if (r < n && nodes_[r].key < nodes_[m].key) m = r;
      if (m == i) break;
      std::swap(nodes_[i], nodes_[m]);
      i = m;
    }
    return top;
  }

  bool empty() const { return nodes_.empty(); }

 private:
  std::vector<HeapNode<K>> nodes_;
};

template <typename K>
void kway_merge(const K** runs, const int64_t* lens, int32_t nruns, K* out) {
  RunHeap<K> heap(nruns);
  std::vector<int64_t> pos(nruns, 0);
  for (int32_t r = 0; r < nruns; ++r) {
    if (lens[r] > 0) heap.push(runs[r][0], r);
  }
  int64_t o = 0;
  while (!heap.empty()) {
    HeapNode<K> top = heap.pop();
    out[o++] = top.key;
    int64_t p = ++pos[top.run];
    if (p < lens[top.run]) heap.push(runs[top.run][p], top.run);
  }
}

template <typename K>
void kway_merge_kv(const K** kruns, const uint8_t** vruns, const int64_t* lens,
                   int32_t nruns, int32_t pbytes, K* out_k, uint8_t* out_v) {
  RunHeap<K> heap(nruns);
  std::vector<int64_t> pos(nruns, 0);
  for (int32_t r = 0; r < nruns; ++r) {
    if (lens[r] > 0) heap.push(kruns[r][0], r);
  }
  int64_t o = 0;
  while (!heap.empty()) {
    HeapNode<K> top = heap.pop();
    int64_t p = pos[top.run];
    out_k[o] = top.key;
    std::memcpy(out_v + o * pbytes, vruns[top.run] + p * pbytes, pbytes);
    ++o;
    if (++pos[top.run] < lens[top.run])
      heap.push(kruns[top.run][pos[top.run]], top.run);
  }
}

// Parallel range partitioning shared by the key-only and record merges:
// range-partition the OUTPUT by key splitters, then hand each contiguous
// range to `spawn_range`.  Splitter t is the median of the runs'
// t/T-quantile keys — medians of coordinate-wise nondecreasing vectors are
// nondecreasing, so range starts are monotone and every range is a valid
// contiguous slice of each run (ties land left of the splitter via a
// consistent lower_bound on every run).  Balance is approximate (exact
// balance is unnecessary for correctness or near-linear speedup).
//
//   key_at(r, i) -> Key            the i-th key of run r
//   lb(r, key) -> int64_t          lower_bound position of key in run r
//   spawn_range(lo, hi, offset)    merge rows [lo[r], hi[r]) of every run
//                                  into the output at element `offset`
template <typename Key, typename KeyAt, typename LowerBound, typename Spawn>
void parallel_range_partition(const int64_t* lens, int32_t nruns,
                              int32_t nthreads, KeyAt key_at, LowerBound lb,
                              Spawn spawn_range) {
  // Boundary positions per (thread, run): bounds[t][r], plus the final end.
  std::vector<std::vector<int64_t>> bounds(nthreads + 1,
                                           std::vector<int64_t>(nruns, 0));
  for (int32_t r = 0; r < nruns; ++r) bounds[nthreads][r] = lens[r];
  for (int32_t t = 1; t < nthreads; ++t) {
    std::vector<Key> cands;
    cands.reserve(nruns);
    for (int32_t r = 0; r < nruns; ++r) {
      if (lens[r] > 0) cands.push_back(key_at(r, lens[r] * t / nthreads));
    }
    if (cands.empty()) continue;
    std::nth_element(cands.begin(), cands.begin() + cands.size() / 2,
                     cands.end());
    Key split = cands[cands.size() / 2];
    for (int32_t r = 0; r < nruns; ++r) bounds[t][r] = lb(r, split);
  }
  int64_t offset = 0;
  for (int32_t t = 0; t < nthreads; ++t) {
    int64_t range = 0;
    for (int32_t r = 0; r < nruns; ++r)
      range += bounds[t + 1][r] - bounds[t][r];
    if (range > 0) spawn_range(bounds[t], bounds[t + 1], offset);
    offset += range;
  }
}

template <typename K>
void kway_merge_parallel(const K** runs, const int64_t* lens, int32_t nruns,
                         K* out, int32_t nthreads) {
  int64_t total = 0;
  for (int32_t r = 0; r < nruns; ++r) total += lens[r];
  if (nthreads <= 1 || total < (1 << 20) || nruns < 2) {
    kway_merge<K>(runs, lens, nruns, out);
    return;
  }
  std::vector<std::thread> ths;
  parallel_range_partition<K>(
      lens, nruns, nthreads,
      [&](int32_t r, int64_t i) { return runs[r][i]; },
      [&](int32_t r, K key) {
        return std::lower_bound(runs[r], runs[r] + lens[r], key) - runs[r];
      },
      [&](const std::vector<int64_t>& lo, const std::vector<int64_t>& hi,
          int64_t offset) {
        std::vector<const K*> sub(nruns);
        std::vector<int64_t> sublen(nruns);
        for (int32_t r = 0; r < nruns; ++r) {
          sub[r] = runs[r] + lo[r];
          sublen[r] = hi[r] - lo[r];
        }
        ths.emplace_back(
            [sub = std::move(sub), sublen = std::move(sublen), nruns,
             dst = out + offset]() mutable {
              kway_merge<K>(sub.data(), sublen.data(), nruns, dst);
            });
      });
  for (auto& th : ths) th.join();
}

// Two-level key: TeraSort's full 10-byte key as an 8-byte big-endian-packed
// primary plus a 2-byte secondary (key bytes 8-9).  A single u64 cannot hold
// all 80 bits, so the heap orders (k1, k2) lexicographically.
struct Key2 {
  uint64_t k1;
  uint16_t k2;
  bool operator<(const Key2& o) const {
    return k1 < o.k1 || (k1 == o.k1 && k2 < o.k2);
  }
  bool operator<=(const Key2& o) const { return !(o < *this); }
};

// K-way merge of record runs ordered by the two-level key.  Key outputs are
// optional (nullptr skips them) — the usual caller only wants the merged
// 100-byte records, with key bytes already inside the payload.
void kway_merge_kv2(const uint64_t** k1runs, const uint16_t** k2runs,
                    const uint8_t** vruns, const int64_t* lens, int32_t nruns,
                    int32_t pbytes, uint64_t* out_k1, uint16_t* out_k2,
                    uint8_t* out_v) {
  RunHeap<Key2> heap(nruns);
  std::vector<int64_t> pos(nruns, 0);
  for (int32_t r = 0; r < nruns; ++r) {
    if (lens[r] > 0) heap.push({k1runs[r][0], k2runs[r][0]}, r);
  }
  int64_t o = 0;
  while (!heap.empty()) {
    HeapNode<Key2> top = heap.pop();
    int64_t p = pos[top.run];
    if (out_k1) out_k1[o] = top.key.k1;
    if (out_k2) out_k2[o] = top.key.k2;
    std::memcpy(out_v + o * pbytes, vruns[top.run] + p * pbytes, pbytes);
    ++o;
    if (++pos[top.run] < lens[top.run]) {
      int64_t q = pos[top.run];
      heap.push({k1runs[top.run][q], k2runs[top.run][q]}, top.run);
    }
  }
}

int64_t lower_bound_pair(const uint64_t* k1, const uint16_t* k2, int64_t len,
                         Key2 v) {
  int64_t lo = 0, hi = len;
  while (lo < hi) {
    int64_t m = lo + (hi - lo) / 2;
    if (Key2{k1[m], k2[m]} < v) lo = m + 1;
    else hi = m;
  }
  return lo;
}

// Threaded variant of the record merge: the shared range partitioning with
// splitters and boundaries on the (k1, k2) pair.
void kway_merge_kv2_parallel(const uint64_t** k1runs, const uint16_t** k2runs,
                             const uint8_t** vruns, const int64_t* lens,
                             int32_t nruns, int32_t pbytes, uint64_t* out_k1,
                             uint16_t* out_k2, uint8_t* out_v,
                             int32_t nthreads) {
  int64_t total = 0;
  for (int32_t r = 0; r < nruns; ++r) total += lens[r];
  if (nthreads <= 1 || total < (1 << 20) || nruns < 2) {
    kway_merge_kv2(k1runs, k2runs, vruns, lens, nruns, pbytes, out_k1, out_k2,
                   out_v);
    return;
  }
  std::vector<std::thread> ths;
  parallel_range_partition<Key2>(
      lens, nruns, nthreads,
      [&](int32_t r, int64_t i) {
        return Key2{k1runs[r][i], k2runs[r][i]};
      },
      [&](int32_t r, Key2 key) {
        return lower_bound_pair(k1runs[r], k2runs[r], lens[r], key);
      },
      [&](const std::vector<int64_t>& lo, const std::vector<int64_t>& hi,
          int64_t offset) {
        std::vector<const uint64_t*> s1(nruns);
        std::vector<const uint16_t*> s2(nruns);
        std::vector<const uint8_t*> sv(nruns);
        std::vector<int64_t> sublen(nruns);
        for (int32_t r = 0; r < nruns; ++r) {
          s1[r] = k1runs[r] + lo[r];
          s2[r] = k2runs[r] + lo[r];
          sv[r] = vruns[r] + lo[r] * pbytes;
          sublen[r] = hi[r] - lo[r];
        }
        uint64_t* o1 = out_k1 ? out_k1 + offset : nullptr;
        uint16_t* o2 = out_k2 ? out_k2 + offset : nullptr;
        uint8_t* ov = out_v + offset * pbytes;
        ths.emplace_back([s1 = std::move(s1), s2 = std::move(s2),
                          sv = std::move(sv), sublen = std::move(sublen),
                          nruns, pbytes, o1, o2, ov]() mutable {
          kway_merge_kv2(s1.data(), s2.data(), sv.data(), sublen.data(),
                         nruns, pbytes, o1, o2, ov);
        });
      });
  for (auto& th : ths) th.join();
}

// ---------------------------------------------------------------------------
// Worker liveness table.
// ---------------------------------------------------------------------------

class WorkerTable {
 public:
  WorkerTable(int32_t n, double timeout_s)
      : timeout_s_(timeout_s), alive_(n, 1), last_hb_(n, 0.0), deaths_(0) {}

  void heartbeat(int32_t w, double now) {
    std::lock_guard<std::mutex> g(mu_);
    if (valid(w)) last_hb_[w] = now;
  }

  int32_t is_alive(int32_t w) {
    std::lock_guard<std::mutex> g(mu_);
    return valid(w) ? alive_[w] : 0;
  }

  void mark_dead(int32_t w) {
    std::lock_guard<std::mutex> g(mu_);
    if (valid(w) && alive_[w]) {
      alive_[w] = 0;
      ++deaths_;
    }
  }

  // Linear scan from 0 (server.c:368-384 semantics); -1 when none live.
  int32_t first_live(int32_t exclude) {
    std::lock_guard<std::mutex> g(mu_);
    for (int32_t i = 0; i < (int32_t)alive_.size(); ++i) {
      if (i != exclude && alive_[i]) return i;
    }
    return -1;
  }

  int32_t check_heartbeats(double now, int32_t* newly_dead) {
    std::lock_guard<std::mutex> g(mu_);
    int32_t count = 0;
    for (int32_t i = 0; i < (int32_t)alive_.size(); ++i) {
      if (alive_[i] && now - last_hb_[i] > timeout_s_) {
        alive_[i] = 0;
        ++deaths_;
        if (newly_dead) newly_dead[count] = i;
        ++count;
      }
    }
    return count;
  }

  // Per-job optimistic revival (server.c:222,278).
  void revive_all(double now) {
    std::lock_guard<std::mutex> g(mu_);
    for (size_t i = 0; i < alive_.size(); ++i) {
      alive_[i] = 1;
      last_hb_[i] = now;
    }
  }

  int32_t death_count() {
    std::lock_guard<std::mutex> g(mu_);
    return deaths_;
  }

  int32_t live_count() {
    std::lock_guard<std::mutex> g(mu_);
    int32_t c = 0;
    for (int32_t a : alive_) c += a;
    return c;
  }

 private:
  bool valid(int32_t w) const { return w >= 0 && w < (int32_t)alive_.size(); }

  std::mutex mu_;
  double timeout_s_;
  std::vector<int32_t> alive_;
  std::vector<double> last_hb_;
  int32_t deaths_;
};

}  // namespace

extern "C" {

void dsort_kway_merge_i32(const int32_t** runs, const int64_t* lens,
                          int32_t nruns, int32_t* out) {
  kway_merge<int32_t>(runs, lens, nruns, out);
}

void dsort_kway_merge_i64(const int64_t** runs, const int64_t* lens,
                          int32_t nruns, int64_t* out) {
  kway_merge<int64_t>(runs, lens, nruns, out);
}

void dsort_kway_merge_u64(const uint64_t** runs, const int64_t* lens,
                          int32_t nruns, uint64_t* out) {
  kway_merge<uint64_t>(runs, lens, nruns, out);
}

void dsort_kway_merge_u32(const uint32_t** runs, const int64_t* lens,
                          int32_t nruns, uint32_t* out) {
  kway_merge<uint32_t>(runs, lens, nruns, out);
}

// uint16 carries mapped float16 keys (ops.float_order), so out-of-core
// float16 sorts keep the streaming native merge instead of falling back to
// an in-memory host merge.
void dsort_kway_merge_u16(const uint16_t** runs, const int64_t* lens,
                          int32_t nruns, uint16_t* out) {
  kway_merge<uint16_t>(runs, lens, nruns, out);
}

void dsort_kway_merge_par_i32(const int32_t** runs, const int64_t* lens,
                              int32_t nruns, int32_t* out, int32_t nthreads) {
  kway_merge_parallel<int32_t>(runs, lens, nruns, out, nthreads);
}

void dsort_kway_merge_par_i64(const int64_t** runs, const int64_t* lens,
                              int32_t nruns, int64_t* out, int32_t nthreads) {
  kway_merge_parallel<int64_t>(runs, lens, nruns, out, nthreads);
}

void dsort_kway_merge_par_u64(const uint64_t** runs, const int64_t* lens,
                              int32_t nruns, uint64_t* out, int32_t nthreads) {
  kway_merge_parallel<uint64_t>(runs, lens, nruns, out, nthreads);
}

void dsort_kway_merge_par_u32(const uint32_t** runs, const int64_t* lens,
                              int32_t nruns, uint32_t* out, int32_t nthreads) {
  kway_merge_parallel<uint32_t>(runs, lens, nruns, out, nthreads);
}

void dsort_kway_merge_par_u16(const uint16_t** runs, const int64_t* lens,
                              int32_t nruns, uint16_t* out, int32_t nthreads) {
  kway_merge_parallel<uint16_t>(runs, lens, nruns, out, nthreads);
}

void dsort_kway_merge_kv_u64(const uint64_t** kruns, const uint8_t** vruns,
                             const int64_t* lens, int32_t nruns, int32_t pbytes,
                             uint64_t* out_k, uint8_t* out_v) {
  kway_merge_kv<uint64_t>(kruns, vruns, lens, nruns, pbytes, out_k, out_v);
}

void dsort_kway_merge_kv_i64(const int64_t** kruns, const uint8_t** vruns,
                             const int64_t* lens, int32_t nruns, int32_t pbytes,
                             int64_t* out_k, uint8_t* out_v) {
  kway_merge_kv<int64_t>(kruns, vruns, lens, nruns, pbytes, out_k, out_v);
}

void dsort_kway_merge_kv2_u64(const uint64_t** k1runs, const uint16_t** k2runs,
                              const uint8_t** vruns, const int64_t* lens,
                              int32_t nruns, int32_t pbytes, uint64_t* out_k1,
                              uint16_t* out_k2, uint8_t* out_v) {
  kway_merge_kv2(k1runs, k2runs, vruns, lens, nruns, pbytes, out_k1, out_k2,
                 out_v);
}

void dsort_kway_merge_kv2_par_u64(const uint64_t** k1runs,
                                  const uint16_t** k2runs,
                                  const uint8_t** vruns, const int64_t* lens,
                                  int32_t nruns, int32_t pbytes,
                                  uint64_t* out_k1, uint16_t* out_k2,
                                  uint8_t* out_v, int32_t nthreads) {
  kway_merge_kv2_parallel(k1runs, k2runs, vruns, lens, nruns, pbytes, out_k1,
                          out_k2, out_v, nthreads);
}

void* dsort_table_create(int32_t n, double heartbeat_timeout_s) {
  return new WorkerTable(n, heartbeat_timeout_s);
}

void dsort_table_destroy(void* t) { delete static_cast<WorkerTable*>(t); }

void dsort_table_heartbeat(void* t, int32_t w, double now) {
  static_cast<WorkerTable*>(t)->heartbeat(w, now);
}

int32_t dsort_table_is_alive(void* t, int32_t w) {
  return static_cast<WorkerTable*>(t)->is_alive(w);
}

void dsort_table_mark_dead(void* t, int32_t w) {
  static_cast<WorkerTable*>(t)->mark_dead(w);
}

int32_t dsort_table_first_live(void* t, int32_t exclude) {
  return static_cast<WorkerTable*>(t)->first_live(exclude);
}

int32_t dsort_table_check_heartbeats(void* t, double now, int32_t* newly_dead) {
  return static_cast<WorkerTable*>(t)->check_heartbeats(now, newly_dead);
}

void dsort_table_revive_all(void* t, double now) {
  static_cast<WorkerTable*>(t)->revive_all(now);
}

int32_t dsort_table_death_count(void* t) {
  return static_cast<WorkerTable*>(t)->death_count();
}

int32_t dsort_table_live_count(void* t) {
  return static_cast<WorkerTable*>(t)->live_count();
}

}  // extern "C"
