"""Native C++ runtime bindings: k-way merge, worker table, TCP coordinator."""

from dsort_tpu.runtime import native  # noqa: F401
from dsort_tpu.runtime.coordinator import NativeCoordinator  # noqa: F401
