"""ctypes bindings to libdsort_native (no pybind11 in this image).

Wraps the native k-way merge (the O(N log k) replacement of the reference's
O(N*k) ``merge_chunks``, ``server.c:481-524``) and the native worker liveness
table.  The library is built from ``dsort_tpu/runtime/native/`` via make; if
the .so is missing we attempt one best-effort build and otherwise report
unavailable so pure-Python fallbacks take over.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_DIR, "libdsort_native.so")
_lib = None
_lib_lock = threading.Lock()
_tried = False


def _load():
    global _lib, _tried
    with _lib_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            if not _make():
                return None
        try:
            _lib = _open_and_bind()
        except (OSError, AttributeError):
            # Missing .so symbols mean a stale prebuilt library from an older
            # source tree — rebuild once and retry before giving up.
            try:
                if _make():
                    _lib = _open_and_bind()
            except (OSError, AttributeError):
                _lib = None
        return _lib


def _make() -> bool:
    try:
        subprocess.run(
            ["make", "-B", "-C", _DIR], capture_output=True, timeout=120, check=True
        )
        return True
    except Exception:
        return False


def _open_and_bind():
    lib = ctypes.CDLL(_LIB_PATH)
    # K-way merge signatures.
    for name in ("i32", "i64", "u64", "u32", "u16"):
        fn = getattr(lib, f"dsort_kway_merge_{name}")
        fn.restype = None
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        par = getattr(lib, f"dsort_kway_merge_par_{name}")
        par.restype = None
        par.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
    for name in ("u64", "i64"):
        fn = getattr(lib, f"dsort_kway_merge_kv_{name}")
        fn.restype = None
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
    lib.dsort_kway_merge_kv2_u64.restype = None
    lib.dsort_kway_merge_kv2_u64.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.dsort_kway_merge_kv2_par_u64.restype = None
    lib.dsort_kway_merge_kv2_par_u64.argtypes = (
        lib.dsort_kway_merge_kv2_u64.argtypes + [ctypes.c_int32]
    )
    lib.dsort_table_create.restype = ctypes.c_void_p
    lib.dsort_table_create.argtypes = [ctypes.c_int32, ctypes.c_double]
    lib.dsort_table_destroy.argtypes = [ctypes.c_void_p]
    lib.dsort_table_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
    lib.dsort_table_is_alive.restype = ctypes.c_int32
    lib.dsort_table_is_alive.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dsort_table_mark_dead.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dsort_table_first_live.restype = ctypes.c_int32
    lib.dsort_table_first_live.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dsort_table_check_heartbeats.restype = ctypes.c_int32
    lib.dsort_table_check_heartbeats.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.POINTER(ctypes.c_int32),
    ]
    lib.dsort_table_revive_all.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.dsort_table_death_count.restype = ctypes.c_int32
    lib.dsort_table_death_count.argtypes = [ctypes.c_void_p]
    lib.dsort_table_live_count.restype = ctypes.c_int32
    lib.dsort_table_live_count.argtypes = [ctypes.c_void_p]
    # Coordinator.
    lib.dsort_coord_create.restype = ctypes.c_void_p
    lib.dsort_coord_create.argtypes = [ctypes.c_uint16, ctypes.c_double]
    lib.dsort_coord_port.restype = ctypes.c_int32
    lib.dsort_coord_port.argtypes = [ctypes.c_void_p]
    lib.dsort_coord_wait_workers.restype = ctypes.c_int32
    lib.dsort_coord_wait_workers.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
    lib.dsort_coord_num_live.restype = ctypes.c_int32
    lib.dsort_coord_num_live.argtypes = [ctypes.c_void_p]
    lib.dsort_coord_submit.restype = ctypes.c_int32
    lib.dsort_coord_submit.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dsort_coord_collect.restype = ctypes.c_int64
    lib.dsort_coord_collect.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double,
    ]
    lib.dsort_coord_kill_worker.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dsort_coord_reassignments.restype = ctypes.c_int32
    lib.dsort_coord_reassignments.argtypes = [ctypes.c_void_p]
    lib.dsort_coord_drain_events.restype = ctypes.c_int64
    lib.dsort_coord_drain_events.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.dsort_coord_shutdown.argtypes = [ctypes.c_void_p]
    lib.dsort_coord_destroy.argtypes = [ctypes.c_void_p]
    # ASCII int ingest/egress (textio.cpp).
    lib.dsort_count_ints.restype = ctypes.c_int64
    lib.dsort_count_ints.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    for name in ("i32", "i64", "u32", "u64"):
        fn = getattr(lib, f"dsort_parse_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        fn = getattr(lib, f"dsort_format_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        fn = getattr(lib, f"dsort_parse_mt_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ]
        fn = getattr(lib, f"dsort_format_mt_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
        ]
    # Validation primitives (the valsort role).
    lib.dsort_fnv_multiset.restype = ctypes.c_uint64
    lib.dsort_fnv_multiset.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.dsort_check_order_be.restype = ctypes.c_int64
    lib.dsort_check_order_be.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
    ]
    return lib


def available() -> bool:
    return _load() is not None


_MERGE_FNS = {
    np.dtype(np.int32): "dsort_kway_merge_i32",
    np.dtype(np.int64): "dsort_kway_merge_i64",
    np.dtype(np.uint64): "dsort_kway_merge_u64",
    np.dtype(np.uint32): "dsort_kway_merge_u32",
    np.dtype(np.uint16): "dsort_kway_merge_u16",
}
_MERGE_KV_FNS = {
    np.dtype(np.uint64): "dsort_kway_merge_kv_u64",
    np.dtype(np.int64): "dsort_kway_merge_kv_i64",
}


def supports_dtype(dtype) -> bool:
    return np.dtype(dtype) in _MERGE_FNS


def _run_ptrs(runs: list[np.ndarray]):
    arr_t = ctypes.c_void_p * len(runs)
    ptrs = arr_t(*[r.ctypes.data_as(ctypes.c_void_p) for r in runs])
    lens = (ctypes.c_int64 * len(runs))(*[len(r) for r in runs])
    return ptrs, lens


def kway_merge(
    runs: list[np.ndarray],
    out: np.ndarray | None = None,
    threads: int | None = None,
) -> np.ndarray:
    """Heap k-way merge of sorted runs in native code.

    ``out``, if given, receives the merge in place (it may be a disk-backed
    ``np.memmap`` — the out-of-core egress path of `models.external_sort`).
    Large merges (>= 2^20 elements, >= 2 runs) range-partition the output by
    key splitters and merge on ``threads`` std::threads (default: the host's
    core count, capped at 16); pass ``threads=1`` to force the serial path.
    """
    lib = _load()
    runs = [np.ascontiguousarray(r) for r in runs]
    dtype = runs[0].dtype
    if dtype not in _MERGE_FNS:  # fail fast, before any output allocation
        raise TypeError(f"native merge does not support {dtype}; see supports_dtype")
    total = sum(len(r) for r in runs)
    if out is None:
        out = np.empty(total, dtype=dtype)
    elif (
        len(out) != total
        or out.dtype != dtype
        or not out.flags.c_contiguous
        or not out.flags.writeable
    ):
        raise ValueError(
            f"out must be writable C-contiguous {dtype}[{total}], "
            f"got {out.dtype}[{len(out)}]"
        )
    if threads is None:
        threads = min(os.cpu_count() or 1, 16)
    ptrs, lens = _run_ptrs(runs)
    if threads > 1:
        fn = getattr(lib, _MERGE_FNS[dtype].replace("merge_", "merge_par_"))
        fn(ptrs, lens, len(runs), out.ctypes.data_as(ctypes.c_void_p), threads)
    else:
        fn = getattr(lib, _MERGE_FNS[dtype])
        fn(ptrs, lens, len(runs), out.ctypes.data_as(ctypes.c_void_p))
    return out


def kway_merge_kv(
    key_runs: list[np.ndarray], val_runs: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Native k-way merge of (key, fixed-width payload) sorted runs."""
    lib = _load()
    key_runs = [np.ascontiguousarray(r) for r in key_runs]
    val_runs = [np.ascontiguousarray(r) for r in val_runs]
    dtype = key_runs[0].dtype
    fn = getattr(lib, _MERGE_KV_FNS[dtype])
    pbytes = int(val_runs[0][0].nbytes) if len(val_runs[0]) else int(
        np.prod(val_runs[0].shape[1:]) * val_runs[0].itemsize
    )
    total = sum(len(r) for r in key_runs)
    out_k = np.empty(total, dtype=dtype)
    out_v = np.empty((total,) + val_runs[0].shape[1:], dtype=val_runs[0].dtype)
    kptrs, lens = _run_ptrs(key_runs)
    vptrs, _ = _run_ptrs(val_runs)
    fn(kptrs, vptrs, lens, len(key_runs), pbytes,
       out_k.ctypes.data_as(ctypes.c_void_p), out_v.ctypes.data_as(ctypes.c_void_p))
    return out_k, out_v


def kway_merge_kv2(
    k1_runs: list[np.ndarray],
    k2_runs: list[np.ndarray],
    val_runs: list[np.ndarray],
    out_v: np.ndarray | None = None,
    want_keys: bool = False,
    threads: int | None = None,
) -> tuple[np.ndarray | None, np.ndarray | None, np.ndarray]:
    """Native merge of record runs ordered by a two-level (u64, u16) key.

    This is the out-of-core TeraSort merge: the full 10-byte key does not
    fit one machine word, so runs carry an 8-byte big-endian-packed primary
    (``k1``, uint64) and the 2-byte tail (``k2``, uint16).  Payload rows
    (typically whole 100-byte records) stream into ``out_v`` — which may be
    a disk-backed memmap.  Key outputs are skipped unless ``want_keys``
    (the records already contain their key bytes).
    """
    lib = _load()
    k1_runs = [np.ascontiguousarray(r, dtype=np.uint64) for r in k1_runs]
    k2_runs = [np.ascontiguousarray(r, dtype=np.uint16) for r in k2_runs]
    val_runs = [np.ascontiguousarray(r) for r in val_runs]
    if not (len(k1_runs) == len(k2_runs) == len(val_runs)):
        raise ValueError("k1/k2/val run counts differ")
    for k1, k2, v in zip(k1_runs, k2_runs, val_runs):
        if not (len(k1) == len(k2) == len(v)):
            raise ValueError(
                f"run lengths differ: k1={len(k1)} k2={len(k2)} v={len(v)}"
            )
        # Row shape/dtype must match across runs: pbytes below is taken from
        # val_runs[0], so a mismatched run would be strided wrong in native
        # code (silent record corruption / out-of-bounds reads).
        if v.shape[1:] != val_runs[0].shape[1:] or v.dtype != val_runs[0].dtype:
            raise ValueError(
                f"val run layout differs: {v.dtype}{v.shape[1:]} vs "
                f"{val_runs[0].dtype}{val_runs[0].shape[1:]}"
            )
    row = val_runs[0].shape[1:]
    pbytes = int(np.prod(row) * val_runs[0].itemsize)
    total = sum(len(r) for r in k1_runs)
    if out_v is None:
        out_v = np.empty((total,) + row, dtype=val_runs[0].dtype)
    elif (
        len(out_v) != total
        or out_v.shape[1:] != row
        or out_v.dtype != val_runs[0].dtype
        or not out_v.flags.c_contiguous
        or not out_v.flags.writeable
    ):
        raise ValueError(
            f"out_v must be writable C-contiguous {val_runs[0].dtype}"
            f"[{total}, {row}], got {out_v.dtype}{out_v.shape}"
        )
    out_k1 = np.empty(total, np.uint64) if want_keys else None
    out_k2 = np.empty(total, np.uint16) if want_keys else None
    if threads is None:
        threads = min(os.cpu_count() or 1, 16)
    k1ptrs, lens = _run_ptrs(k1_runs)
    k2ptrs, _ = _run_ptrs(k2_runs)
    vptrs, _ = _run_ptrs(val_runs)
    args = (
        k1ptrs, k2ptrs, vptrs, lens, len(k1_runs), pbytes,
        out_k1.ctypes.data_as(ctypes.c_void_p) if want_keys else None,
        out_k2.ctypes.data_as(ctypes.c_void_p) if want_keys else None,
        out_v.ctypes.data_as(ctypes.c_void_p),
    )
    if threads > 1:
        lib.dsort_kway_merge_kv2_par_u64(*args, threads)
    else:
        lib.dsort_kway_merge_kv2_u64(*args)
    return out_k1, out_k2, out_v


_TEXT_SUFFIX = {
    np.dtype(np.int32): "i32",
    np.dtype(np.int64): "i64",
    np.dtype(np.uint32): "u32",
    np.dtype(np.uint64): "u64",
}
# Worst-case formatted width (digits + sign) + newline per element.
_TEXT_WIDTH = {"i32": 12, "i64": 21, "u32": 11, "u64": 21}


def supports_text_dtype(dtype) -> bool:
    return np.dtype(dtype) in _TEXT_SUFFIX


def parse_ints_text(data: bytes, dtype) -> np.ndarray:
    """Parse whitespace-separated ASCII integers natively.

    Capacity comes from the newline count (exact for the reference's
    one-int-per-line format, a single memchr-speed scan); only if tokens are
    packed denser than lines does the parser report capacity overflow and a
    native token-count pass (the reference's count/rewind/fill ingest shape,
    ``server.c:171-182``) sizes the retry exactly.  Raises ValueError on
    malformed tokens or range overflow.
    """
    lib = _load()
    dtype = np.dtype(dtype)
    threads = _text_threads()
    fn = getattr(lib, f"dsort_parse_mt_{_TEXT_SUFFIX[dtype]}")
    needed = ctypes.c_int64(-1)
    cap = data.count(b"\n") + 1
    out = np.empty(cap, dtype=dtype)
    n = fn(
        data, len(data), out.ctypes.data_as(ctypes.c_void_p), cap, threads,
        ctypes.byref(needed),
    )
    if n == -3:  # PARSE_OVERFLOW_CAP: tokens denser than lines; size exactly
        cap = needed.value if needed.value >= 0 else lib.dsort_count_ints(
            data, len(data)
        )
        if cap == -2:
            raise OverflowError("integer text does not fit any 64-bit dtype")
        if cap < 0:
            raise ValueError(f"malformed integer text (native error {cap})")
        out = np.empty(cap, dtype=dtype)
        n = fn(
            data, len(data), out.ctypes.data_as(ctypes.c_void_p), cap, threads,
            ctypes.byref(needed),
        )
    if n == -2:
        # Distinct exception type: callers must NOT recover from this by
        # falling back to a lossier parser (np.loadtxt wraps out-of-range
        # values to INT_MIN silently — a sort over corrupted keys).
        raise OverflowError(
            f"integer text does not fit dtype {dtype}; use a wider KEY_DTYPE"
        )
    if n < 0:
        raise ValueError(f"malformed integer text (native error {n})")
    if n == len(out):
        return out
    if len(out) - n <= 1:  # the usual trailing-newline slack: keep the view
        return out[:n]
    # Bigger slack (blank-line-heavy files): copy so the trimmed result does
    # not pin the oversized allocation alive.
    return out[:n].copy()


def _text_threads() -> int:
    return min(8, os.cpu_count() or 1)


def format_ints_text(data: np.ndarray) -> bytes:
    """Format a 1-D int array as one-int-per-line ASCII, natively (parallel
    for large arrays: ranges format at worst-case stride, then compact)."""
    lib = _load()
    data = np.ascontiguousarray(data)
    suffix = _TEXT_SUFFIX[data.dtype]
    width = _TEXT_WIDTH[suffix]
    cap = len(data) * width + 1
    buf = ctypes.create_string_buffer(cap)
    fn = getattr(lib, f"dsort_format_mt_{suffix}")
    written = fn(
        data.ctypes.data_as(ctypes.c_void_p), len(data), buf, cap, width,
        _text_threads(),
    )
    if written < 0:
        raise ValueError("native int formatting failed (buffer overflow)")
    return ctypes.string_at(buf, written)


def _as_ptr(buf):
    """(void* pointer, keepalive) for an ndarray or bytes-like buffer."""
    if isinstance(buf, np.ndarray):
        buf = np.ascontiguousarray(buf)
        return buf.ctypes.data_as(ctypes.c_void_p), buf
    return ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p), buf


def fnv_multiset(buf, nrec: int, rec_bytes: int) -> int:
    """Order-independent multiset checksum: sum mod 2^64 of per-record FNV-1a.

    Equal record multisets give equal sums regardless of order — comparing a
    sort's input and output proves the output is a permutation of the input
    (the valsort checksum role).
    """
    lib = _load()
    ptr, keep = _as_ptr(buf)
    return int(lib.dsort_fnv_multiset(ptr, nrec, rec_bytes))


def check_order_be(buf, nrec: int, rec_bytes: int, key_bytes: int) -> int:
    """First 1-based index whose big-endian key dips below its predecessor's,
    or -1 when the chunk is nondecreasing (TeraSort byte-string key order)."""
    lib = _load()
    ptr, keep = _as_ptr(buf)
    return int(lib.dsort_check_order_be(ptr, nrec, rec_bytes, key_bytes))


# Native coordinator event lines ("t=<secs> ev=<type> [w=<i>] [task=<id>]",
# one per state transition, drained via dsort_coord_drain_events) map onto
# the Python journal's registered types (utils.events.EVENT_TYPES).
_COORD_EVENT_TYPES = {
    "worker_join": "worker_join",
    "worker_dead": "worker_dead",
    "reassign": "reassign",
    "attempt_start": "attempt_start",
    "task_done": "task_done",
    "job_failed": "job_failed",
    "heartbeat_lapse": "heartbeat_lapse",
}


def parse_coord_events(text: str) -> list[dict]:
    """Parse drained native event lines into journal-shaped dicts.

    Each dict has ``type`` (a registered `utils.events` type), ``mono``
    (the coordinator's steady-clock stamp — the same CLOCK_MONOTONIC base
    as ``time.monotonic`` in this process), ``t`` (converted to WALL clock
    via the current mono→wall offset, so native records merge with
    Python-emitted events' ``t``), and the line's integer fields
    (``worker``, ``task``).  Malformed lines are skipped, never raised: the
    journal is a diagnostic surface and must not take down a job.
    """
    wall_offset = time.time() - time.monotonic()
    out = []
    for line in text.splitlines():
        kv = {}
        for tok in line.split():
            if "=" not in tok:
                kv = None
                break
            k, _, v = tok.partition("=")
            kv[k] = v
        if not kv or "ev" not in kv or "t" not in kv:
            continue
        etype = _COORD_EVENT_TYPES.get(kv["ev"])
        if etype is None:
            continue
        try:
            mono = float(kv["t"])
            rec = {"type": etype, "t": mono + wall_offset, "mono": mono}
            if "w" in kv:
                rec["worker"] = int(kv["w"])
            if "task" in kv:
                rec["task"] = int(kv["task"])
        except ValueError:
            continue
        out.append(rec)
    return out


def coord_drain_events(handle) -> list[dict]:
    """Drain and parse the native coordinator's buffered event lines."""
    lib = _load()
    if lib is None:
        return []
    out: list[dict] = []
    while True:
        buf = ctypes.create_string_buffer(1 << 16)
        n = lib.dsort_coord_drain_events(handle, buf, len(buf))
        if n <= 0:
            return out
        out.extend(parse_coord_events(buf.raw[:n].decode("ascii", "replace")))


class NativeWorkerTable:
    """Native-backed drop-in for `scheduler.liveness.WorkerTable`."""

    def __init__(self, num_workers: int, heartbeat_timeout_s: float = 10.0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dsort_table_create(num_workers, heartbeat_timeout_s)
        self.num_workers = num_workers

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.dsort_table_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def heartbeat(self, worker: int) -> None:
        self._lib.dsort_table_heartbeat(self._h, worker, time.monotonic())

    def is_alive(self, worker: int) -> bool:
        return bool(self._lib.dsort_table_is_alive(self._h, worker))

    def mark_dead(self, worker: int) -> None:
        self._lib.dsort_table_mark_dead(self._h, worker)

    def first_live(self, exclude: int | None = None) -> int | None:
        r = self._lib.dsort_table_first_live(
            self._h, -1 if exclude is None else exclude
        )
        return None if r < 0 else r

    def live_workers(self) -> list[int]:
        return [i for i in range(self.num_workers) if self.is_alive(i)]

    def check_heartbeats(self) -> list[int]:
        out = (ctypes.c_int32 * self.num_workers)()
        n = self._lib.dsort_table_check_heartbeats(self._h, time.monotonic(), out)
        return list(out[:n])

    def revive_all(self) -> None:
        self._lib.dsort_table_revive_all(self._h, time.monotonic())

    @property
    def death_count(self) -> int:
        return self._lib.dsort_table_death_count(self._h)
