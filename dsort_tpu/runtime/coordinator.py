"""High-level driver over the native TCP coordinator (multi-host DCN path).

`NativeCoordinator` is the framework's cross-host execution mode: the C++
coordinator owns membership, liveness, dispatch, and reassignment (the
reference master's L1-L3, ``server.c:120-157,297-477``); Python owns the data
plane (partition, merge) and each worker process owns a JAX device.  The wire
carries length-prefixed frames, so no key value is reserved (the reference
reserves ``-1``, ``server.c:405-406``).
"""

from __future__ import annotations

import ctypes

import numpy as np

from dsort_tpu.data.partition import partition
from dsort_tpu.scheduler.fault import JobFailedError
from dsort_tpu.utils.logging import get_logger
from dsort_tpu.utils.metrics import Metrics, PhaseTimer

log = get_logger("coordinator")


class NativeCoordinator:
    """Owns a native coordinator instance serving one cluster of workers."""

    def __init__(self, port: int = 0, heartbeat_timeout_s: float = 10.0):
        from dsort_tpu.runtime import native

        lib = native._load()
        if lib is None:
            raise RuntimeError("native library unavailable; run make in runtime/native")
        self._lib = lib
        self._h = lib.dsort_coord_create(port, heartbeat_timeout_s)
        if not self._h:
            raise OSError(f"could not bind coordinator port {port}")

    @property
    def port(self) -> int:
        return self._lib.dsort_coord_port(self._h)

    def wait_workers(self, n: int, timeout_s: float = 30.0) -> int:
        """Block until n workers have joined (the reference's accept x4,
        server.c:148-157 — but late joiners are allowed too)."""
        got = self._lib.dsort_coord_wait_workers(self._h, n, timeout_s)
        if got < n:
            raise TimeoutError(f"only {got}/{n} workers joined the cluster")
        return got

    @property
    def num_live(self) -> int:
        return self._lib.dsort_coord_num_live(self._h)

    @property
    def reassignments(self) -> int:
        return self._lib.dsort_coord_reassignments(self._h)

    def kill_worker(self, w: int) -> None:
        """Fault injection: hard-close worker w's connection."""
        self._lib.dsort_coord_kill_worker(self._h, w)

    def drain_events(self, metrics: Metrics | None) -> list[dict]:
        """Pull the C++ coordinator's buffered state-transition lines.

        Each compact native line ("t=... ev=worker_dead w=1") becomes one
        record on the job's event journal (when ``metrics.journal`` is
        attached), so the native cluster's fault timeline — joins, deaths,
        reassignments, heartbeat lapses — lands in the SAME stream as every
        other execution mode's.  Returns the parsed records either way.
        """
        from dsort_tpu.runtime import native

        if not self._h:
            return []
        recs = native.coord_drain_events(self._h)
        journal = getattr(metrics, "journal", None)
        if journal is not None:
            if recs:
                # Alignment handshake for the journal merger (obs.merge):
                # the drained native records carry the coordinator's steady
                # clock, and this emit's own (wall, mono) pair anchors that
                # base explicitly in the same journal.
                metrics.event("clock_sync", source="native_coordinator")
            for r in recs:
                fields = {
                    k: v for k, v in r.items() if k not in ("type", "t", "mono")
                }
                journal.ingest(r["t"], r["mono"], r["type"], **fields)
        return recs

    def submit(self, task_id: int, data: np.ndarray) -> None:
        data = np.ascontiguousarray(data)
        rc = self._lib.dsort_coord_submit(
            self._h, task_id, data.ctypes.data_as(ctypes.c_void_p), data.nbytes
        )
        if rc != 0:
            raise JobFailedError(f"no live workers to take task {task_id}")

    def collect(self, task_id: int, dtype, max_elems: int, timeout_s: float = 60.0) -> np.ndarray:
        dtype = np.dtype(dtype)
        out = np.empty(max_elems, dtype=dtype)
        n = self._lib.dsort_coord_collect(
            self._h, task_id, out.ctypes.data_as(ctypes.c_void_p),
            out.nbytes, timeout_s,
        )
        if n == -1:
            raise JobFailedError(f"task {task_id} failed: no live workers remain")
        if n == -2:
            raise TimeoutError(f"task {task_id} did not complete in {timeout_s}s")
        if n < 0:
            raise RuntimeError(f"collect({task_id}) error {n}")
        assert n % dtype.itemsize == 0
        return out[: n // dtype.itemsize].copy()

    def run_job(
        self, data: np.ndarray, num_shards: int, metrics: Metrics | None = None
    ) -> np.ndarray:
        """One distributed sort job over the worker cluster.

        Partition -> dispatch shards (coordinator handles reassignment) ->
        collect pinned per-shard results -> native k-way merge.
        """
        from dsort_tpu.runtime import native

        metrics = metrics if metrics is not None else Metrics()
        timer = PhaseTimer(metrics)
        data = np.asarray(data)
        # Float keys need no ops.float_order mapping here: this path has no
        # sentinel padding (shards are exact-size), workers sort NaNs last
        # (lax/np total order), and the host merge falls back to numpy's
        # NaN-last sort — mapping would also break the workers' spawn-time
        # --dtype frame contract, which the coordinator cannot renegotiate.
        with timer.phase("partition"):
            shards = partition(data, num_shards)
        try:
            with timer.phase("dispatch"):
                for i, s in enumerate(shards):
                    self.submit(i, s)
            with timer.phase("collect"):
                results = [
                    self.collect(i, data.dtype, max_elems=len(shards[i]) or 1)
                    for i in range(num_shards)
                ]
        finally:
            # Drain even when the job fails: the buffered worker_dead /
            # reassign / job_failed lines are the explanation of the failure
            # and must reach the journal.
            metrics.bump("reassignments", self.reassignments)
            self.drain_events(metrics)
        with timer.phase("merge"):
            if native.supports_dtype(data.dtype):
                out = native.kway_merge([r for r in results if len(r)] or [data[:0]])
            else:
                from dsort_tpu.ops.merge import merge_sorted_host

                out = merge_sorted_host(results)
        return out

    def shutdown(self) -> None:
        if self._h:
            self._lib.dsort_coord_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
