# Convenience targets — the CI story in four words: make lint, make test.
PY ?= python
NATIVE := dsort_tpu/runtime/native

lint:  ## project-native static analysis (registry/concurrency/tracing/...)
	$(PY) -m dsort_tpu.cli lint

lint-sarif:  ## lint as SARIF 2.1.0 (code-scanning upload) -> lint.sarif
	$(PY) -m dsort_tpu.cli lint --format sarif > lint.sarif

baseline:  ## record current findings as tolerated (ship this file EMPTY)
	$(PY) -m dsort_tpu.cli lint --write-baseline

test:  ## tier-1 suite (excludes slow/sanitizer tests)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

bench-smoke:  ## device-resident sort + on-device validate on the 8-device cpu mesh
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --device-resident --n 200000 --reps 2 \
	--journal /tmp/dsort_bench_smoke.jsonl

bench-exchange-smoke:  ## three-way alltoall/ring/fused exchange A/B (uniform + zipf + kv) on the 8-device cpu mesh
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --exchange-ab --n 200000 --reps 2 \
	--journal /tmp/dsort_bench_exchange_smoke.jsonl

# The fused-ring smoke is the same one-copy A/B harness — the fused arm
# rides --exchange-ab so the three schedules always measure the same data.
bench-fused-smoke: bench-exchange-smoke  ## fused Pallas ring kernel A/B smoke (alias of bench-exchange-smoke)

fused-smoke: bench-fused-smoke  ## alias: ISSUE 11 CI name for the fused-ring smoke

serve-smoke:  ## mixed small/large two-tenant workload through the real serving queue (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --serve-mixed --n 400000 --reps 1 \
	--journal /tmp/dsort_serve_smoke.jsonl

fleet-smoke:  ## federated serving: 2 local agents behind a fleet controller, locality/random/health routing A/B + telemetry-overhead baseline (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --fleet-mixed --n 20000 --reps 1 \
	--journal /tmp/dsort_fleet_smoke.jsonl

spec-smoke:  ## explicit-state model check of the fleet protocol (bounded, backend-free, seconds)
	$(PY) -m dsort_tpu.cli spec check --max-states 12000

profile-smoke:  ## introspection-plane cost proof: ring sort with vs without journal+ledger+memwatch (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --analyze-smoke --n 1048576 --reps 2 \
	--journal /tmp/dsort_profile_smoke.jsonl

external-smoke:  ## out-of-core wave pipeline: 8x-over-budget sort, overlap A/B + mid-wave fault drill (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --external-wave --n 262144 --reps 1 \
	--journal /tmp/dsort_external_smoke.jsonl

coded-smoke:  ## coded-redundancy failure A/B: redundancy=1 vs 2, healthy vs one injected loss, bit-identical gate (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --coded-ab --n 131072 --reps 1 \
	--journal /tmp/dsort_coded_smoke.jsonl

coded-v2-smoke:  ## coded v2 acceptance A/B: parity-vs-replicate wire premium, per-mode loss drills, straggler p99 race (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --coded-v2-ab --n 131072 --reps 1 \
	--journal /tmp/dsort_coded_v2_smoke.jsonl

autotune-smoke:  ## closed-loop planner A/B: hand-set alltoall/ring vs planner-chosen exchange, bit-identical + correct-pick gate (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --autotune-ab --n 131072 --reps 1 \
	--journal /tmp/dsort_autotune_smoke.jsonl

hier-smoke:  ## two-level pod exchange A/B: flat ring vs hier at simulated HxD topologies + device/host-loss drills, bit-identical + DCN-reduction gate (8-device cpu mesh)
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m dsort_tpu.cli bench --hier-ab --n 131072 --reps 1 \
	--journal /tmp/dsort_hier_smoke.jsonl

# Regression diff over versioned bench artifacts (tolerance ladder:
# ok >= 0.95 > noise >= 0.80 > regression >= 0.50 > severe); exits 1 on
# severe (STRICT=1: also on regression).  Backend-free.
OLD ?= BENCH_r05_preview.jsonl
NEW ?= BENCH_r06.jsonl
bench-compare:  ## diff two bench artifacts: make bench-compare OLD=a NEW=b [STRICT=1]
	$(PY) bench.py --compare $(OLD) $(NEW) $(if $(STRICT),--strict,)

bench-history:  ## the whole in-tree BENCH_r*.jsonl perf trajectory as one metric x PR table
	$(PY) bench.py --history

native:  ## build libdsort_native.so
	$(MAKE) -C $(NATIVE)

tsan:  ## build + run the native selftest under ThreadSanitizer
	$(MAKE) -C $(NATIVE) tsan-selftest
	$(NATIVE)/selftest_tsan

asan:  ## build + run the native selftest under AddressSanitizer
	$(MAKE) -C $(NATIVE) asan-selftest
	$(NATIVE)/selftest_asan

ubsan:  ## build + run the native selftest under UBSanitizer
	$(MAKE) -C $(NATIVE) ubsan-selftest
	$(NATIVE)/selftest_ubsan

sanitize: tsan asan ubsan  ## all three sanitizer selftest runs

.PHONY: lint lint-sarif baseline test bench-smoke bench-exchange-smoke bench-fused-smoke fused-smoke serve-smoke fleet-smoke spec-smoke profile-smoke external-smoke coded-smoke coded-v2-smoke autotune-smoke hier-smoke bench-compare bench-history native tsan asan ubsan sanitize
