# Convenience targets — the CI story in four words: make lint, make test.
PY ?= python
NATIVE := dsort_tpu/runtime/native

lint:  ## project-native static analysis (registry/concurrency/tracing/...)
	$(PY) -m dsort_tpu.cli lint

baseline:  ## record current findings as tolerated (ship this file EMPTY)
	$(PY) -m dsort_tpu.cli lint --write-baseline

test:  ## tier-1 suite (excludes slow/sanitizer tests)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

native:  ## build libdsort_native.so
	$(MAKE) -C $(NATIVE)

tsan:  ## build + run the native selftest under ThreadSanitizer
	$(MAKE) -C $(NATIVE) tsan-selftest
	$(NATIVE)/selftest_tsan

asan:  ## build + run the native selftest under AddressSanitizer
	$(MAKE) -C $(NATIVE) asan-selftest
	$(NATIVE)/selftest_asan

ubsan:  ## build + run the native selftest under UBSanitizer
	$(MAKE) -C $(NATIVE) ubsan-selftest
	$(NATIVE)/selftest_ubsan

sanitize: tsan asan ubsan  ## all three sanitizer selftest runs

.PHONY: lint baseline test native tsan asan ubsan sanitize
