"""Benchmark entry point — prints ONE JSON line with the headline metric.

Headline: single-chip sort throughput (keys/sec) on uniform random int32,
compared against the reference system's measured end-to-end throughput of
~4.4e4 keys/s total (BASELINE.md: 16,384 int32 in ~374 ms across 4 CPU
workers over localhost TCP — its maximum supported job size).

Env knobs: DSORT_BENCH_N (default 2^24 keys), DSORT_BENCH_REPS (default 5).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_KEYS_PER_SEC = 16_384 / 0.374  # BASELINE.md measured, ~4.38e4


def _ensure_responsive_backend() -> None:
    """Guard against a wedged accelerator runtime.

    Backend init can hang indefinitely if the device tunnel is in a bad state
    (observed: a killed client can leave the chip claim stuck for a long
    time).  Probe device init in a subprocess with a timeout; on failure,
    re-exec this benchmark on the CPU backend so the driver always gets its
    one JSON line instead of a hang.
    """
    if os.environ.get("DSORT_BENCH_NO_PROBE"):
        return
    timeout = float(os.environ.get("DSORT_BENCH_DEVICE_TIMEOUT", 180))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            check=True,
        )
        return  # backend healthy; run in-process normally
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the TPU site hook
    env["DSORT_BENCH_NO_PROBE"] = "1"
    env["DSORT_BENCH_FALLBACK"] = "cpu"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    _ensure_responsive_backend()

    import jax
    import jax.numpy as jnp

    from dsort_tpu.ops.local_sort import sort_keys

    n = int(os.environ.get("DSORT_BENCH_N", 1 << 24))
    reps = int(os.environ.get("DSORT_BENCH_REPS", 5))

    rng = np.random.default_rng(0)
    host = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    x = jnp.asarray(host)

    f = jax.jit(sort_keys)
    y = f(x)
    y.block_until_ready()  # compile + warm
    # Sanity: correct against the numpy oracle on a sample window.
    out = np.asarray(y)
    assert (np.diff(out[: 1 << 16]) >= 0).all(), "bench output not sorted"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    keys_per_sec = n / dt

    chip = jax.devices()[0].platform
    suffix = "_fallback" if os.environ.get("DSORT_BENCH_FALLBACK") else ""
    print(
        json.dumps(
            {
                "metric": f"sort_throughput_int32_{n}_keys_single_chip_{chip}{suffix}",
                "value": round(keys_per_sec, 1),
                "unit": "keys/sec",
                "vs_baseline": round(keys_per_sec / REFERENCE_KEYS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
