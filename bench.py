"""Benchmark entry point — prints ONE JSON line with the headline metric.

Headline: single-chip sort throughput (keys/sec) on uniform random int32,
compared against the reference system's measured end-to-end throughput of
~4.4e4 keys/s total (BASELINE.md: 16,384 int32 in ~374 ms across 4 CPU
workers over localhost TCP — its maximum supported job size).

Env knobs: DSORT_BENCH_N (default 2^24 keys), DSORT_BENCH_REPS (default 3),
DSORT_BENCH_CHAIN (default 16 — sorts chained inside one jitted program per
timed call; the reported per-sort time is total/chain, amortizing the ~70 ms
host<->device dispatch round-trip).

N=2^24 is the measured sweet spot: 740 Mkeys/s there vs 621 at 2^25; at 2^26
XLA's sort drops to ~48 Mkeys/s (memory cliff) — see README "Performance".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_KEYS_PER_SEC = 16_384 / 0.374  # BASELINE.md measured, ~4.38e4


def _ensure_responsive_backend() -> None:
    """Guard against a wedged accelerator runtime.

    Backend init can hang indefinitely if the device tunnel is in a bad state
    (observed: a killed client can leave the chip claim stuck for a long
    time).  Probe device init in a subprocess with a timeout; on failure,
    re-exec this benchmark on the CPU backend so the driver always gets its
    one JSON line instead of a hang.
    """
    if os.environ.get("DSORT_BENCH_NO_PROBE"):
        return
    timeout = float(os.environ.get("DSORT_BENCH_DEVICE_TIMEOUT", 180))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            check=True,
        )
        return  # backend healthy; run in-process normally
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the TPU site hook
    env["DSORT_BENCH_NO_PROBE"] = "1"
    env["DSORT_BENCH_FALLBACK"] = "cpu"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    _ensure_responsive_backend()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from dsort_tpu.ops.local_sort import sort_keys

    n = int(os.environ.get("DSORT_BENCH_N", 1 << 24))
    reps = int(os.environ.get("DSORT_BENCH_REPS", 3))
    chain = int(os.environ.get("DSORT_BENCH_CHAIN", 16))
    if chain < 1:
        raise SystemExit("DSORT_BENCH_CHAIN must be >= 1")

    rng = np.random.default_rng(0)
    host = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    x = jnp.asarray(host)

    # Timing methodology: `block_until_ready` is unreliable through the axon
    # device tunnel (observed returning before execution completes), and a
    # single dispatch carries a ~70 ms host<->device round-trip that would
    # swamp the ~40 ms on-chip sort.  So (a) completion is forced by a tiny
    # device->host slice copy, which cannot return early, and (b) `chain`
    # data-dependent sorts run inside ONE jitted program (each iteration
    # re-sorts the previous result XOR the loop index; comparator-network
    # sort time is input-independent, so chaining is distribution-fair) and
    # the per-sort time is total/chain, amortizing the dispatch overhead.
    f = jax.jit(
        lambda a: lax.fori_loop(0, chain, lambda i, v: sort_keys(v ^ i), a)
    )
    y = f(x)  # compile + warm
    out_head = np.asarray(y[: 1 << 16])  # forces completion
    assert (np.diff(out_head) >= 0).all(), "bench output not sorted"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _ = np.asarray(f(x)[-1:])  # tiny D2H copy = true completion barrier
        times.append(time.perf_counter() - t0)
    # min, not median: timer noise here (relay-tunnel jitter on the
    # completion barrier) is strictly additive, so the fastest rep is the
    # closest estimate of the true cost (observed 630-740 Mkeys/s run-to-run
    # spread under median).
    dt = float(min(times)) / chain
    keys_per_sec = n / dt

    chip = jax.devices()[0].platform
    suffix = "_fallback" if os.environ.get("DSORT_BENCH_FALLBACK") else ""
    print(
        json.dumps(
            {
                "metric": f"sort_throughput_int32_{n}_keys_single_chip_{chip}{suffix}",
                "value": round(keys_per_sec, 1),
                "unit": "keys/sec",
                "vs_baseline": round(keys_per_sec / REFERENCE_KEYS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
