"""Benchmark entry point — one JSON line per metric, headline first.

Headline: single-chip sort throughput (keys/sec) on uniform random int32 at
2^24 keys, measured on the framework's own block-bitonic Pallas kernel
(``ops.block_sort`` — fused-pass network, see its module docstring), compared
against the reference system's measured end-to-end throughput of ~4.4e4
keys/s (BASELINE.md: 16,384 int32 in ~374 ms across 4 CPU workers over
localhost TCP — its maximum supported job size).

Secondary lines: the same workload on XLA's built-in ``lax.sort``, the 2^26
size (round 1's "memory cliff"), 2^23 int64 (the lexicographic-planes path),
the TeraSort kv local phase (two-level key + 90 B payload permute, rec/s),
the post-shuffle merge comparison (block_merge_runs vs full re-sort vs the
jnp bitonic tree at the SPMD shape), the BASELINE config ladder (5 configs),
a CPU-mesh Zipf+injected-failure line (the config5 capability the single
real chip cannot exercise), and a phase split of one SPMD sort.

Timing methodology (r4 — reconciling the r3 chain-vs-slope gap):
`block_until_ready` is unreliable through the axon device tunnel, and a
single dispatch carries a ~70-100 ms host<->device round-trip.  So (a)
completion is forced by a tiny device->host slice copy, and (b) ``chain``
data-dependent sorts run inside ONE jitted program (each iteration re-sorts
the previous result XOR the loop index; comparator networks are
data-oblivious, so chaining is distribution-fair).  The r3 artifact divided
one chain's total by its length, which still charges the fixed dispatch +
tunnel round-trip (~100 ms) to the sorts: at chain 48 that is ~2 ms/sort —
exactly the r3 "1.52 recorded vs 1.95 slope" 22% gap.  r4 headline lines
therefore time TWO chain lengths and report the SLOPE
((T(c2)-T(c1))/(c2-c1)) as the per-sort figure — the fixed overhead cancels
— and carry the chained figure plus the measured per-dispatch overhead in
the same line so both methodologies stay visible.  min over reps, not
median: tunnel jitter is one-sided additive noise.

Env knobs: DSORT_BENCH_N (default 2^24), DSORT_BENCH_REPS (default 3),
DSORT_BENCH_CHAIN (default 48; the short chain is chain//6),
DSORT_BENCH_KERNEL ("block" | "lax" | ...), DSORT_BENCH_SUITE (default 1;
0 = headline lines only).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_KEYS_PER_SEC = 16_384 / 0.374  # BASELINE.md measured, ~4.38e4

# -- artifact schema (VERDICT r5 missing #1 successor: self-parsing) --------
#
# Every artifact this driver emits opens with ONE header line carrying the
# schema version and the line contract; `bench.py --check ARTIFACT`
# round-trips every line against the header it finds (or against the v0
# default below for pre-header artifacts), so a reader — or CI — can verify
# an artifact without knowing which bench revision wrote it.

BENCH_SCHEMA_VERSION = 1
#: Keys every metric line must carry, with their JSON types.
BENCH_SCHEMA_REQUIRED = {"metric": "str", "value": "num", "unit": "str"}
#: Known optional fields: PRESENT fields must match these types; fields not
#: listed here are free-form extras (allowed — lines carry workload context).
BENCH_SCHEMA_FIELD_TYPES = {
    "vs_baseline": "num",
    "chained_value": "num",
    "method": "str",
    "kernel": "str",
    "fixed_overhead_ms_per_dispatch": "num",
    "validated_ok": "bool",
    "bit_identical": "bool",
    "host_fraction": "num",
    "host_fraction_link": "num",
    "host_fraction_code": "num",
    "expected_transfer_s": "num",
    "phases_seconds": "obj",
    "ms_per_merge": "obj",
    "lines": "obj",
    "l": "obj",
    "bytes_on_wire": "num",
    "bytes_on_wire_alltoall": "num",
    "bytes_saved": "num",
    "speedup_vs_alltoall": "num",
    "speedup_vs_relay_e2e": "num",
    "capacity_retries": "num",
    "capacity_retries_alltoall": "num",
    "capacity_retries_ring": "num",
    "mesh_reforms": "num",
    "exchange": "str",
    "error": "str",
    "skipped": "str",
    # Serving-layer mixed-workload row (`dsort bench --serve-mixed`):
    "p95_queue_wait_ms": "num",
    "fairness_p95_ratio": "num",
    "cache_hit_rate": "num",
    "speedup_vs_serial": "num",
    "jobs": "num",
    "tenants": "num",
    "prewarmed": "num",
    "slices": "num",
    # Introspection-plane cost row (`dsort bench --analyze-smoke`, ISSUE 9):
    "overhead_frac": "num",
    "bare_keys_per_sec": "num",
    "journaled_keys_per_sec": "num",
    "dominant_phase": "str",
    "skew_ratio_zipf": "num",
    "skew_ratio_uniform": "num",
    "hbm_watermark_bytes": "num",
    "introspection_ok": "bool",
    # Out-of-core wave-pipeline rows (`dsort bench --external-wave`, ISSUE 10):
    "over_hbm_factor": "num",
    "num_waves": "num",
    "overlap_speedup": "num",
    "resume_fraction": "num",
    "runs_resorted": "num",
    # Fused-ring A/B rows (`dsort bench --exchange-ab` fused arm, ISSUE 11):
    "dispatches_per_exchange": "num",
    "dispatches_per_exchange_ring": "num",
    "ring_keys_per_sec": "num",
    "speedup_vs_ring": "num",
    "fused_launches_per_sort": "num",
    # Federated fleet row (`dsort bench --fleet-mixed`, ISSUE 12):
    "agents": "num",
    "cache_hit_rate_random": "num",
    "speedup_vs_random": "num",
    "rerouted": "num",
    # Health-plane rows (`dsort bench --fleet-mixed` health arm, ISSUE 14):
    "telemetry_overhead_frac": "num",
    "health_verdicts": "num",
    "speedup_vs_locality": "num",
    # Coded-redundancy rows (`dsort bench --coded-ab`, ISSUE 15):
    "throughput_under_failure_ratio": "num",
    "rerun_failure_ratio": "num",
    "replica_overhead_frac": "num",
    "redundancy": "num",
    "coded_recoveries": "num",
    "coded_replica_bytes": "num",
    "recovered_keys": "num",
    "baseline_keys_per_sec": "num",
    "rerun_keys_per_sec": "num",
    # Closed-loop planner A/B rows (`dsort bench --autotune-ab`, ISSUE 16):
    "chosen_exchange": "str",
    "expected_exchange": "str",
    "best_arm": "str",
    "best_keys_per_sec": "num",
    "alltoall_keys_per_sec": "num",
    "autotune_vs_best": "num",
    "plan_decisions": "num",
}

_SCHEMA_TYPE_CHECKS = {
    "str": lambda v: isinstance(v, str),
    "num": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "bool": lambda v: isinstance(v, bool),
    "obj": lambda v: isinstance(v, dict),
}


def _schema_header() -> dict:
    return {
        "bench_schema": BENCH_SCHEMA_VERSION,
        "required": BENCH_SCHEMA_REQUIRED,
        "field_types": BENCH_SCHEMA_FIELD_TYPES,
    }


def check_artifact(path: str) -> list[str]:
    """Validate one artifact; returns a list of violations (empty = OK).

    Each line must be a JSON object that survives a dumps/loads round trip;
    metric lines must carry the required keys at the required types, and
    any field the schema knows must match its declared type.  A header line
    (``bench_schema``) switches validation to the contract it embeds —
    artifacts written before the header default to the v0 contract (same
    required keys, this file's known-field table).
    """
    errors: list[str] = []
    required = dict(BENCH_SCHEMA_REQUIRED)
    field_types = dict(BENCH_SCHEMA_FIELD_TYPES)
    try:
        with open(path, encoding="utf-8") as f:
            raw_lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    saw_metric = False
    for lineno, raw in enumerate(raw_lines, 1):
        line = raw.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"{path}:{lineno}: not JSON: {e}")
            continue
        if not isinstance(obj, dict):
            errors.append(f"{path}:{lineno}: line is not a JSON object")
            continue
        if json.loads(json.dumps(obj)) != obj:
            errors.append(f"{path}:{lineno}: does not round-trip")  # pragma: no cover
            continue
        if "bench_schema" in obj:
            if saw_metric:
                errors.append(
                    f"{path}:{lineno}: schema header after metric lines"
                )
            if not isinstance(obj["bench_schema"], int):
                errors.append(f"{path}:{lineno}: bench_schema not an int")
            if isinstance(obj.get("required"), dict):
                required = obj["required"]
            if isinstance(obj.get("field_types"), dict):
                field_types = obj["field_types"]
            continue
        saw_metric = True
        for key, typ in required.items():
            if key not in obj:
                errors.append(f"{path}:{lineno}: missing required {key!r}")
            elif not _SCHEMA_TYPE_CHECKS.get(typ, lambda v: True)(obj[key]):
                errors.append(
                    f"{path}:{lineno}: {key!r} is not of type {typ!r}"
                )
        for key, typ in field_types.items():
            if key in obj and not _SCHEMA_TYPE_CHECKS.get(
                typ, lambda v: True
            )(obj[key]):
                errors.append(
                    f"{path}:{lineno}: {key!r} is not of type {typ!r}"
                )
    return errors


# -- artifact regression diff (`bench.py --compare OLD NEW`) -----------------
#
# The in-tree BENCH_*.jsonl artifacts are a trajectory; this is the tool
# that reads it.  Metrics match by name; throughput-like units compare as
# new/old ratios and classify on the tolerance ladder below.  Sub-unity
# ratios up to `noise` are expected between sessions (the tunnel-jitter
# doctrine in the module docstring); `regression`/`severe` mean a change
# that needs an explanation in the PR that shipped it.

#: (floor ratio, class) — first floor the ratio clears, scanning down.
COMPARE_LADDER: tuple[tuple[float, str], ...] = (
    (0.95, "ok"),
    (0.80, "noise"),
    (0.50, "regression"),
    (0.0, "severe"),
)

#: Units where value is a rate (higher = better) and a ratio is meaningful.
_RATE_UNITS = {"keys/sec", "rec/sec", "MB/s"}


def classify_ratio(ratio: float) -> str:
    for floor, label in COMPARE_LADDER:
        if ratio >= floor:
            return label
    return "severe"


def _artifact_metrics(path: str) -> dict[str, dict]:
    """Metric lines of one artifact, keyed by metric name (summary/header
    lines dropped; duplicate names keep their first occurrence, matching
    the summary's disambiguation doctrine)."""
    out: dict[str, dict] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(obj, dict) or "metric" not in obj:
                continue
            if obj["metric"] in ("summary", "compact_summary"):
                continue
            out.setdefault(obj["metric"], obj)
    return out


def compare_artifacts(old_path: str, new_path: str) -> list[dict]:
    """Regression rows for every metric the two artifacts share.

    Each row: ``{"metric", "unit", "old", "new", "ratio", "class"}`` for
    rate units; non-rate units (ratios, counters) report ``class:"info"``.
    Metrics present on only one side report as ``added``/``removed`` —
    silently narrowing coverage is itself a regression signal.
    """
    old, new = _artifact_metrics(old_path), _artifact_metrics(new_path)
    rows: list[dict] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o is None or n is None:
            rows.append(
                {"metric": name, "class": "added" if o is None else "removed"}
            )
            continue
        row = {
            "metric": name, "unit": n.get("unit"),
            "old": o.get("value"), "new": n.get("value"),
        }
        # A zero/errored side makes the ratio meaningless — an error line's
        # value is 0.0 by convention; call it out instead of dividing.
        if "error" in o or "error" in n or not o.get("value"):
            row["class"] = "error" if ("error" in o or "error" in n) else "info"
        elif n.get("unit") in _RATE_UNITS and o.get("unit") == n.get("unit"):
            ratio = float(n["value"]) / float(o["value"])
            row["ratio"] = round(ratio, 3)
            row["class"] = classify_ratio(ratio)
        else:
            row["class"] = "info"
        rows.append(row)
    return rows


def _compare_main(argv: list[str]) -> int:
    """``bench.py --compare OLD NEW [--strict]``: print rows, summarize.

    Exit 1 on any ``severe`` row (``--strict``: also on ``regression``);
    the ladder classes in between are reported, not fatal — session noise
    must not turn CI red.  Backend-free, like ``--check``.
    """
    strict = "--strict" in argv
    paths = [a for a in argv if a != "--strict"]
    if len(paths) != 2:
        print(
            "usage: bench.py --compare OLD NEW [--strict]", file=sys.stderr
        )
        return 2
    rows = compare_artifacts(paths[0], paths[1])
    if not rows:
        print(f"no metric lines found in {paths[0]} / {paths[1]}",
              file=sys.stderr)
        return 2
    counts: dict[str, int] = {}
    for row in rows:
        counts[row["class"]] = counts.get(row["class"], 0) + 1
        print(json.dumps(row), flush=True)
    print(json.dumps({
        "metric": "compare_summary",
        "old": paths[0], "new": paths[1],
        "classes": counts,
        "ladder": [[f, c] for f, c in COMPARE_LADDER],
    }), flush=True)
    bad = counts.get("severe", 0) + (counts.get("regression", 0) if strict else 0)
    return 1 if bad else 0


# -- perf trajectory (`bench.py --history [DIR]`) ----------------------------
#
# The in-tree BENCH_r*.jsonl artifacts record one bench session per PR;
# until now the trajectory across them was only reconstructable by hand
# (pairwise --compare runs).  --history consolidates them into ONE
# metric x artifact table, classifying each consecutive step on the same
# tolerance ladder --compare uses.  Backend-free, like --check.

_HISTORY_GLOB = "BENCH_r*.jsonl"


def history_artifacts(root: str) -> list[str]:
    """In-tree ``BENCH_r*.jsonl`` artifacts, oldest first (by the rNN
    number, then name — previews sort with their round)."""
    import glob as _glob
    import re as _re

    def round_of(path):
        m = _re.search(r"BENCH_r(\d+)", os.path.basename(path))
        return (int(m.group(1)) if m else 0, os.path.basename(path))

    return sorted(
        _glob.glob(os.path.join(root, _HISTORY_GLOB)), key=round_of
    )


def history_rows(root: str) -> dict:
    """The consolidated trajectory: ``{"artifacts": [names...],
    "metrics": {metric: {artifact: {"value", "unit"}}},
    "steps": {metric: [{"frm", "to", "ratio", "class"}...]}}``.

    Steps classify CONSECUTIVE appearances of a metric (they may skip
    artifacts — a metric benched in r07 and r12 classifies r07->r12) on
    the `COMPARE_LADDER`, rate units only; non-rate units report
    ``info``.
    """
    paths = history_artifacts(root)
    names = [os.path.basename(p) for p in paths]
    metrics: dict[str, dict] = {}
    for path, name in zip(paths, names):
        for metric, obj in _artifact_metrics(path).items():
            metrics.setdefault(metric, {})[name] = {
                "value": obj.get("value"), "unit": obj.get("unit"),
            }
    steps: dict[str, list] = {}
    for metric, per in metrics.items():
        seen = [n for n in names if n in per]
        for frm, to in zip(seen, seen[1:]):
            o, n = per[frm], per[to]
            row = {"frm": frm, "to": to}
            if (
                n.get("unit") in _RATE_UNITS
                and o.get("unit") == n.get("unit")
                and o.get("value")
            ):
                ratio = float(n["value"]) / float(o["value"])
                row["ratio"] = round(ratio, 3)
                row["class"] = classify_ratio(ratio)
            else:
                row["class"] = "info"
            steps.setdefault(metric, []).append(row)
    return {"artifacts": names, "metrics": metrics, "steps": steps}


def _history_main(argv: list[str]) -> int:
    """``bench.py --history [DIR]``: print the metric x PR trajectory."""
    root = argv[0] if argv else os.path.dirname(os.path.abspath(__file__))
    if len(argv) > 1:
        print("usage: bench.py --history [DIR]", file=sys.stderr)
        return 2
    hist = history_rows(root)
    if not hist["artifacts"]:
        print(f"no {_HISTORY_GLOB} artifacts under {root}", file=sys.stderr)
        return 2
    cols = [n.replace("BENCH_", "").replace(".jsonl", "")
            for n in hist["artifacts"]]
    head = f"{'metric':<52}" + "".join(f"{c:>14}" for c in cols)
    print(head)
    print("-" * len(head))
    worst: dict[str, int] = {}
    for metric in sorted(hist["metrics"]):
        per = hist["metrics"][metric]
        cells = []
        for name in hist["artifacts"]:
            v = per.get(name, {}).get("value")
            cells.append(f"{v:>14.4g}" if isinstance(v, (int, float))
                         else f"{'-':>14}")
        marks = "".join(
            {"ok": "", "info": "", "noise": "~",
             "regression": "!", "severe": "!!"}.get(s["class"], "")
            for s in hist["steps"].get(metric, ())
        )
        print(f"{(metric + (' ' + marks if marks else ''))[:52]:<52}"
              + "".join(cells))
        for s in hist["steps"].get(metric, ()):
            worst[s["class"]] = worst.get(s["class"], 0) + 1
    print(json.dumps({
        "metric": "history_summary",
        "artifacts": hist["artifacts"],
        "metrics": len(hist["metrics"]),
        "classes": worst,
    }), flush=True)
    return 0


def _ensure_responsive_backend() -> None:
    """Guard against a wedged accelerator runtime.

    Backend init can hang indefinitely if the device tunnel is in a bad state
    (observed: a killed client can leave the chip claim stuck for a long
    time).  Probe device init in a subprocess with a timeout; on failure,
    re-exec this benchmark on the CPU backend so the driver always gets its
    JSON lines instead of a hang.
    """
    if os.environ.get("DSORT_BENCH_NO_PROBE"):
        return
    timeout = float(os.environ.get("DSORT_BENCH_DEVICE_TIMEOUT", 180))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            check=True,
        )
        return  # backend healthy; run in-process normally
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the TPU site hook
    env["DSORT_BENCH_NO_PROBE"] = "1"
    env["DSORT_BENCH_FALLBACK"] = "cpu"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


_EMITTED: list = []  # every line of this run, for the final summary


def _emit_line(line: dict) -> None:
    _EMITTED.append(line)
    print(json.dumps(line), flush=True)


def _emit(metric: str, value: float, unit: str, baseline: bool = True, **extra) -> None:
    line: dict = {"metric": metric, "value": round(value, 1), "unit": unit}
    if baseline:
        line["vs_baseline"] = round(value / REFERENCE_KEYS_PER_SEC, 2)
    line.update(extra)
    _emit_line(line)


def _emit_summary() -> None:
    """LAST lines of the artifact: every headline, full then compact.

    The driver's artifact capture is a bounded TAIL and its ``parsed``
    field is the final JSON line — r4 lost the block-kernel and int64
    headlines to exactly that truncation (VERDICT r4 missing #2), and the
    r5 full summary itself outgrew the 2,000-byte tail (VERDICT r5 missing
    #1).  So TWO summary lines close the artifact: the full summary
    (value/unit/vs_baseline plus the chained cross-check per metric, for
    humans and the preview file), then a final COMPACT line (`
    _compact_summary`: short keys, rounded values, < ~1,500 bytes) so the
    line the driver's tail parser lands on always fits the capture.
    Emitted from a ``finally`` so a mid-suite crash still summarizes the
    lines that did complete.
    """
    if not _EMITTED:
        return
    head = _EMITTED[0]
    lines = {}
    for ln in _EMITTED:
        entry = {"value": ln["value"], "unit": ln["unit"]}
        for k in ("vs_baseline", "chained_value", "kernel", "fastest",
                  "slowdown_at_end", "mesh_reforms", "host_fraction",
                  "skipped", "error"):
            if k in ln:
                entry[k] = ln[k]
        # Two rows may share a metric label (e.g. ladder variants that
        # differ only in the `kernel` extra) — keying by metric alone would
        # silently overwrite one, the exact truncation failure mode this
        # summary exists to prevent (ADVICE r5).  Disambiguate by kernel,
        # then by index, so every emitted line survives into the summary.
        key = ln["metric"]
        if key in lines and "kernel" in ln:
            key = f"{key}#{ln['kernel']}"
        dup = 2
        while key in lines:
            key = f"{ln['metric']}#{dup}"
            dup += 1
        lines[key] = entry
    out = {
        "metric": "summary",
        # value/unit/vs_baseline mirror the HEADLINE line so a parser that
        # only reads the last line still sees the headline figure.
        "value": head["value"],
        "unit": head["unit"],
        "headline": head["metric"],
        "lines": lines,
    }
    if "vs_baseline" in head:
        out["vs_baseline"] = head["vs_baseline"]
    print(json.dumps(out), flush=True)
    print(json.dumps(_compact_summary(_EMITTED)), flush=True)


#: Tokens dropped outright by `_abbrev` — pure noise in a short key.
_ABBREV_NOISE = frozenset(
    {"sort", "throughput", "keys", "records", "single", "chip", "with",
     "sorted", "runs", "end", "to", "the", "injected", "failure", "phase",
     "split"}
)


def _abbrev(metric: str) -> str:
    """Deterministic short key for one metric name (compact summary).

    Powers of two render as ``2pN``, dtypes shorten (``int32`` → ``i32``),
    ``configN`` → ``cN``, noise words drop, everything else keeps its first
    four letters.  Collisions are resolved by the caller (suffixing) — the
    mapping need not be pretty, only small and stable; the FULL summary
    line directly above carries the unabbreviated names.
    """
    out = []
    for tok in metric.split("_"):
        if tok.isdigit():
            n = int(tok)
            if n >= 256 and n & (n - 1) == 0:
                out.append(f"2p{n.bit_length() - 1}")
            else:
                out.append(tok)
        elif tok.startswith(("uint", "int", "float")) and tok[-1].isdigit():
            out.append(
                tok.replace("uint", "u").replace("int", "i")
                .replace("float", "f")
            )
        elif tok.startswith("config"):
            out.append("c" + tok[len("config"):])
        elif tok in _ABBREV_NOISE:
            continue
        else:
            out.append(tok[:4])
    return "".join(out) or "m"


def _sig3(v):
    """3-significant-digit rounding — compact-line values need no more."""
    if not isinstance(v, (int, float)) or v == 0:
        return v
    from math import floor, log10

    return round(v, -int(floor(log10(abs(v)))) + 2)


def _compact_summary(emitted: list) -> dict:
    """The guaranteed-small final artifact line (VERDICT r5 missing #1).

    Short keys (`_abbrev`, deduped), values rounded to 3 significant
    digits, each entry ``[value]`` or ``[value, vs_baseline]`` — nothing
    else.  ~25 bytes/metric keeps even a 40-metric suite far below the
    driver's 2,000-byte tail capture; ``tests/test_bench_summary.py``
    pins the bound at < 1,800 bytes for a 20-metric suite.
    """
    head = emitted[0]
    lines: dict = {}
    for ln in emitted:
        key = _abbrev(ln["metric"])
        while key in lines:
            key += "x"
        entry = [_sig3(ln["value"])]
        if "vs_baseline" in ln:
            entry.append(_sig3(ln["vs_baseline"]))
        lines[key] = entry
    out = {
        "metric": "compact_summary",
        "value": head["value"],
        "unit": head["unit"],
        "l": lines,
    }
    if "vs_baseline" in head:
        out["vs_baseline"] = head["vs_baseline"]
    return out


def _chain_runner(sort_fn, x):
    """One jitted chain executable with a TRACED length.

    The chain length rides as a runtime argument to ``fori_loop``, so the
    short and long chains of a slope pair share a single executable — one
    Mosaic/XLA compile instead of two.  That matters through the remote
    compile service, whose cold-compile time for the full kernel set swings
    from ~1 min to ~10 min between sessions (measured r4); the loop body
    and therefore the per-iteration cost are identical to a static-bound
    chain (XLA lowers both to the same while loop).
    """
    import jax
    from jax import lax

    f = jax.jit(
        lambda a, c: lax.fori_loop(0, c, lambda i, v: sort_fn(v ^ i), a)
    )
    # np.int32 pins the traced length's dtype: a bare Python int is a WEAK
    # scalar whose aval flips int32 -> int64 when the suite enables x64
    # mid-run, silently recompiling the whole chain executable (minutes
    # through a cold compile service) on the next call.
    y = f(x, np.int32(2))  # compile + warm
    out_head = np.asarray(y[: 1 << 16])  # materialize = warm run completed
    assert (np.diff(out_head) >= 0).all(), "bench output not sorted"
    return f


def _chain_total(f, x, chain: int, reps: int) -> float:
    """Total seconds for one ``chain``-length run of a `_chain_runner` (min
    of reps — tunnel jitter is one-sided additive noise)."""
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        # tiny D2H copy = completion barrier; np.int32: see _chain_runner
        _ = np.asarray(f(x, np.int32(chain))[-1:])
        times.append(time.perf_counter() - t0)
    return float(min(times))


def _slope_of(total_fn, c1: int, c2: int):
    """Two-point slope over any total-seconds-per-chain callable.

    Returns ``(per_op_s, fixed_overhead_s | None, chained_per_op_s)``.  The
    slope cancels the fixed dispatch + tunnel round-trip; the chained figure
    (T(c2)/c2) still includes overhead/c2.  If tunnel jitter yields a
    non-positive slope, falls back to the chained figure with
    ``fixed = None`` so emitters can label the line honestly.
    """
    t1, t2 = total_fn(c1), total_fn(c2)
    per = (t2 - t1) / (c2 - c1)
    chained = t2 / c2
    if per <= 0:  # noise swamped the short chain; don't report garbage
        return chained, None, chained
    return per, max(t1 - c1 * per, 0.0), chained


def _slope_fields(per, fixed, chained, n_items, c1, c2) -> dict:
    """The shared reporting contract: method + chained figure + overhead."""
    out = {
        "method": f"chain_slope({c1},{c2})" if fixed is not None
        else "chained_fallback",
        "chained_value": round(n_items / chained, 1),
    }
    if fixed is not None:
        out["fixed_overhead_ms_per_dispatch"] = round(fixed * 1e3, 2)
    return out


def _emit_slope(name: str, n_items: int, unit: str, sort_fn, x, c1, c2, reps,
                baseline: bool = True, **extra):
    """Emit one slope-timed line; returns ``(runner, per, fixed, chained)``
    so callers can re-measure the same executable later (drift sensor)."""
    f = _chain_runner(sort_fn, x)
    per, fixed, chained = _slope_of(
        lambda c: _chain_total(f, x, c, reps), c1, c2
    )
    _emit(
        name, n_items / per, unit, baseline=baseline,
        **_slope_fields(per, fixed, chained, n_items, c1, c2), **extra,
    )
    return f, per, fixed, chained


def _probe_transfer(reps: int, nbytes: int = 32 << 20) -> dict | None:
    """Measure the host<->device link: warm H2D/D2H MB/s + small-RTT.

    The r5 review's scratch probe, productized (VERDICT r5 next #4): one
    32 MB buffer rides device_put (H2D) and np.asarray (D2H) ``reps`` times
    warm — min over reps, the suite's one-sided-jitter doctrine — and an
    8-int32 round-trip measures the fixed per-dispatch RTT.  Bulk timings
    subtract the RTT floor so bandwidth and latency don't double-count.
    Emits one ``transfer_probe_link`` artifact line; returns the figures
    for the phase-split rows' `expected_transfer_s` derivation (None if the
    probe itself failed — the e2e rows then carry no decomposition rather
    than a wrong one).
    """
    import jax

    try:
        host = np.random.default_rng(7).integers(
            0, 255, nbytes, dtype=np.uint8
        )
        tiny = np.zeros(8, np.int32)
        d = jax.device_put(host)
        np.asarray(d[-8:])  # warm both directions + compile the slice
        np.asarray(jax.device_put(tiny)[:1])
        rtts = []
        for _ in range(max(reps, 3)):
            t0 = time.perf_counter()
            np.asarray(jax.device_put(tiny)[:1])
            rtts.append(time.perf_counter() - t0)
        rtt = float(min(rtts))
        h2d = []
        for _ in range(reps):
            t0 = time.perf_counter()
            d = jax.device_put(host)
            np.asarray(d[-8:])  # tiny fetch = completion barrier
            h2d.append(time.perf_counter() - t0)
        d2h = []
        for _ in range(reps):
            # Fresh device array each rep: jax caches the host copy on the
            # Array after the first full np.asarray, and a cached read would
            # measure memcpy, not the link.  The re-put + barrier sit
            # OUTSIDE the timed region.
            dd = jax.device_put(host)
            np.asarray(dd[-8:])
            t0 = time.perf_counter()
            np.asarray(dd)
            d2h.append(time.perf_counter() - t0)
        # 100 us floor: where a direction is effectively free (CPU memcpy),
        # report a ~"nbytes / 100 us" ceiling, not an absurd 1e15 B/s.
        h2d_s = max(float(min(h2d)) - rtt, 1e-4)
        d2h_s = max(float(min(d2h)) - rtt, 1e-4)
        out = {
            "h2d_bytes_per_s": nbytes / h2d_s,
            "d2h_bytes_per_s": nbytes / d2h_s,
            "rtt_s": rtt,
        }
        _emit_line(
            {
                "metric": "transfer_probe_link",
                "value": round(min(out["h2d_bytes_per_s"],
                                   out["d2h_bytes_per_s"]) / 1e6, 1),
                "unit": "MB/s",
                "h2d_mb_per_s": round(out["h2d_bytes_per_s"] / 1e6, 1),
                "d2h_mb_per_s": round(out["d2h_bytes_per_s"] / 1e6, 1),
                "rtt_ms": round(rtt * 1e3, 2),
                "probe_bytes": nbytes,
            }
        )
        return out
    except Exception as e:  # the probe must never sink the artifact
        _emit_line(
            {
                "metric": "transfer_probe_link", "value": 0.0, "unit": "MB/s",
                "error": (str(e).splitlines() or [repr(e)])[0][:200],
            }
        )
        return None


def main() -> None:
    _ensure_responsive_backend()
    # The schema header is the artifact's FIRST line — printed directly
    # (not via _emit_line) so the summary never mistakes it for a metric.
    print(json.dumps(_schema_header()), flush=True)
    try:
        _main_body()
    finally:
        _emit_summary()


def _main_body() -> None:
    import jax

    # Persistent compilation cache: the Pallas kernel set compiles in ~1 min
    # cold; cached reloads take seconds (verified through the axon remote
    # compiler).  Harmless on CPU.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dsort_tpu.ops.local_sort import sort_with_kernel

    n = int(os.environ.get("DSORT_BENCH_N", 1 << 24))
    reps = int(os.environ.get("DSORT_BENCH_REPS", 3))
    chain = int(os.environ.get("DSORT_BENCH_CHAIN", 48))
    if chain < 2:
        raise SystemExit("DSORT_BENCH_CHAIN must be >= 2")
    c_short = max(chain // 6, 1)
    chip = jax.devices()[0].platform
    kernel = os.environ.get("DSORT_BENCH_KERNEL", "block")
    if chip != "tpu" and kernel == "block":
        # The Pallas kernel only *interprets* off-TPU — orders of magnitude
        # slow; the CPU fallback measures lax so the driver still gets lines.
        kernel = "lax"
    suffix = "_fallback" if os.environ.get("DSORT_BENCH_FALLBACK") else ""

    rng = np.random.default_rng(0)
    host = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    x = jax.numpy.asarray(host)

    # Headline: the framework kernel, slope-timed (see module docstring).
    _emit_slope(
        f"sort_throughput_int32_{n}_keys_single_chip_{chip}{suffix}",
        n, "keys/sec",
        lambda v: sort_with_kernel(v, kernel), x, c_short, chain, reps,
        kernel=kernel,
    )

    if os.environ.get("DSORT_BENCH_SUITE", "1") != "1":
        return

    # The round-1 headline kernel (XLA lax.sort) on the same workload, for a
    # like-for-like speedup record in the same artifact.  The runner is kept
    # and re-measured at suite end as the tunnel-drift sensor below.
    hbm_sensor = None
    if kernel != "lax":
        hbm_sensor = _emit_slope(
            f"sort_throughput_int32_{n}_keys_single_chip_{chip}_lax_kernel",
            n, "keys/sec",
            lambda v: sort_with_kernel(v, "lax"), x, c_short, chain, reps,
            kernel="lax",
        )

    # 2^26: round 1's memory cliff (lax.sort fell to ~48 Mkeys/s there).
    if chip == "tpu":
        n26 = 1 << 26
        big = jax.numpy.asarray(
            rng.integers(-(2**31), 2**31 - 1, n26, dtype=np.int64).astype(
                np.int32
            )
        )
        _emit_slope(
            f"sort_throughput_int32_{n26}_keys_single_chip_{chip}",
            n26, "keys/sec",
            lambda v: sort_with_kernel(v, kernel), big,
            max(chain // 24, 1), max(chain // 4, 2), reps,
            kernel=kernel,
        )
        del big

    from dsort_tpu.utils.compat import set_x64

    set_x64(True)  # int64/uint64 lines + config3; via the compat shim (DS501)

    # 2^23 int64 — the lexicographic (hi, lo)-planes path (README's 2.2x-lax
    # claim, now artifact-recorded each round: VERDICT r3 #3).
    if chip == "tpu":
        import jax.numpy as jnp

        n64 = 1 << 23
        h64 = rng.integers(-(2**62), 2**62, n64, dtype=np.int64)
        x64 = jnp.asarray(h64)
        _, per64_blk, fixed64_blk, chained64_blk = _emit_slope(
            f"sort_throughput_int64_{n64}_keys_single_chip_{chip}",
            n64, "keys/sec",
            lambda v: sort_with_kernel(v, kernel), x64, c_short, chain, reps,
            kernel=kernel,
        )
        _, per64_lax, fixed64_lax, chained64_lax = _emit_slope(
            f"sort_throughput_int64_{n64}_keys_single_chip_{chip}_lax_kernel",
            n64, "keys/sec",
            lambda v: sort_with_kernel(v, "lax"), x64, c_short, chain, reps,
            kernel="lax",
        )
        # Same-run block/lax int64 ratio as its OWN artifact field (VERDICT
        # r5 weak #3): the margin thinned to 1.10x in r5 and sessions swing
        # ±10%, so the claim "block beats lax on int64" needs a per-artifact
        # guard, not two rows a reader must divide.  Below 1.05 the ratio is
        # inside the session noise — flag it so a future inversion alerts.
        # Like-for-like comparison (same rule as the drift sensor): slope vs
        # slope only when BOTH slopes were valid; if either fell back to the
        # chained figure (fixed is None), compare chained vs chained so the
        # fixed-overhead share cancels instead of inflating one side.
        if fixed64_blk is not None and fixed64_lax is not None:
            ratio = per64_lax / per64_blk if per64_blk > 0 else 0.0
            ratio_method = "chain_slope"
        else:
            ratio = (
                chained64_lax / chained64_blk if chained64_blk > 0 else 0.0
            )
            ratio_method = "chained_fallback"
        drift = ratio < 1.05
        if drift:
            print(
                f"WARNING: int64 block/lax ratio {ratio:.3f} < 1.05 — the "
                "block kernel's int64 edge is inside session noise this run",
                file=sys.stderr,
            )
        # _emit_line, not _emit: the 1-decimal value rounding there would
        # flatten 1.048 to 1.0 — exactly the precision this guard needs.
        _emit_line(
            {
                "metric": f"int64_block_vs_lax_ratio_{n64}",
                "value": round(ratio, 3),
                "unit": "ratio",
                "kernel": kernel,
                "method": ratio_method,
                **({"drift_warning": True} if drift else {}),
            }
        )
        del x64

    # TeraSort kv local phase: two-level key (uint64 primary + int32
    # secondary) + 90 B payload permute — the exact per-chip work of
    # `_kv_shard_body`'s phase 1 (lax.sort multi-operand carries the
    # permutation; the payload rides one gather).  rec/s, slope-timed.
    if chip == "tpu":
        import jax.numpy as jnp

        from dsort_tpu.ops.local_sort import _apply_perm

        nrec = 1 << 22
        kq = jnp.asarray(rng.integers(0, 2**63, nrec, dtype=np.uint64))
        sq = jnp.asarray(rng.integers(0, 2**16, nrec).astype(np.int32))
        vq = jnp.asarray(rng.integers(0, 255, (nrec, 90), dtype=np.uint8))
        idx = jnp.arange(nrec, dtype=jnp.int32)

        def kv_local(carry, i):
            k, s, v = carry
            ok, os_, perm = jax.lax.sort(
                (k, s, idx), dimension=-1, num_keys=2, is_stable=False
            )
            return (ok ^ i.astype(jnp.uint64), os_, _apply_perm(v, perm, 0))

        # Traced chain length: both slope points share one executable (see
        # _chain_runner).
        fkv = jax.jit(
            lambda k, s, v, c: jax.lax.fori_loop(
                0, c, lambda i, cr: kv_local(cr, i), (k, s, v)
            )
        )
        # np.int32 chain length: see _chain_runner (pins the aval across
        # the x64 flip; bare ints are weak scalars and would recompile).
        np.asarray(fkv(kq, sq, vq, np.int32(2))[2][-1:, -1:])  # warm

        def _kv_chain_total(c: int) -> float:
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                r = fkv(kq, sq, vq, np.int32(c))
                np.asarray(r[2][-1:, -1:])  # completion barrier
                times.append(time.perf_counter() - t0)
            return float(min(times))

        ck1, ck2 = 2, 10
        per, fixed, chained = _slope_of(_kv_chain_total, ck1, ck2)
        _emit(
            f"terasort_local_phase_{nrec}_records_kv",
            nrec / per, "rec/sec", baseline=False,
            **_slope_fields(per, fixed, chained, nrec, ck1, ck2),
            payload_bytes=90,
        )
        del kq, sq, vq

    # Post-shuffle merge comparison at the SPMD shape (P=8 runs of one
    # block): block_merge_runs (enter the network at level 2*run_len) vs the
    # full block_sort re-sort vs the jnp bitonic tree (VERDICT r3 #2).  The
    # `+ i` chain keeps rows sorted (comparator networks are data-oblivious,
    # so the rare int32 wraparound cannot affect timing); correctness is
    # asserted once un-chained.
    if chip == "tpu":
        import jax.numpy as jnp

        from dsort_tpu.ops.bitonic import merge_sorted_runs
        from dsort_tpu.ops.block_sort import block_merge_runs, block_sort

        p_runs, run_len = 8, 1 << 17
        nm = p_runs * run_len
        base = np.sort(
            rng.integers(-(2**31), 2**31 - 1, (p_runs, run_len), dtype=np.int64)
            .astype(np.int32),
            axis=1,
        )
        runs = jnp.asarray(base)
        ref = np.sort(base.reshape(-1))
        assert (np.asarray(block_merge_runs(runs)) == ref).all()

        def _rows_runner(fn_flat):
            f = jax.jit(
                lambda a, c: jax.lax.fori_loop(
                    0, c,
                    lambda i, v: fn_flat(v).reshape(v.shape) + i,
                    a,
                )
            )
            # np.int32: see _chain_runner (pin the aval across the x64 flip)
            np.asarray(f(runs, np.int32(2))[-1:, -1:])  # warm + materialize
            return f

        def _rows_chain_total(f, c: int) -> float:
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                np.asarray(f(runs, np.int32(c))[-1:, -1:])
                times.append(time.perf_counter() - t0)
            return float(min(times))

        import functools

        # us-scale ops need long chains or tunnel jitter swamps the slope
        # (a 24/144 pair once measured the full re-sort at an impossible
        # 0.028 ms/merge); traced lengths make long chains compile-free.
        cm1, cm2 = 96, 576
        variants = {
            "block_merge": lambda v: block_merge_runs(v),
            "full_resort": lambda v: block_sort(v.reshape(-1)),
            "bitonic_jnp": lambda v: merge_sorted_runs(v),
        }
        per_variant = {}
        for name, fn in variants.items():
            f = _rows_runner(fn)
            per, _, _ = _slope_of(functools.partial(_rows_chain_total, f), cm1, cm2)
            per_variant[name] = per
        best = min(per_variant, key=per_variant.get)
        _emit(
            f"merge_phase_{p_runs}x{run_len}_sorted_runs",
            nm / per_variant["block_merge"], "keys/sec", baseline=False,
            method=f"chain_slope({cm1},{cm2})",
            ms_per_merge={
                k: round(v * 1e3, 3) for k, v in per_variant.items()
            },
            fastest=best,
        )
        del runs

    # BASELINE config ladder (5 lines) — end-to-end host->host timings of the
    # public SampleSort API, so these *include* the tunnel round-trip.
    import argparse

    from dsort_tpu import cli as _cli

    _cli._bench_suite(argparse.Namespace(reps=reps, emit=_emit_line))

    # config5's failure-injection capability needs >= 4 devices; the single
    # real chip can't exercise it, so record the CPU-mesh run (Zipf 1M with
    # an injected mid-shuffle device failure and mesh re-form) as a driver
    # artifact line (VERDICT r3 #9).  Timed value includes the re-form and
    # the 7-device recompile — a capability record, not a perf number.
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    # The cpu-mesh subprocesses import dsort_tpu (one via `-m`): pin the
    # repo root on PYTHONPATH so they work from any cwd.
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.abspath(__file__))]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    cpu_script = r"""
import json, time
import jax
jax.config.update("jax_enable_x64", True)  # gen_zipf keys are int64
import numpy as np
from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_zipf
from dsort_tpu.scheduler import FaultInjector, SpmdScheduler
from dsort_tpu.utils.metrics import Metrics
inj = FaultInjector()
sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01), injector=inj)
data = gen_zipf(1 << 20, seed=5)
sched.sort(data)  # warm the 8-device program
inj.fail_once(3, "spmd")
m = Metrics()
t0 = time.perf_counter()
out = sched.sort(data, metrics=m)
dt = time.perf_counter() - t0
assert (np.diff(out) >= 0).all() and len(out) == len(data)
print(json.dumps({
    "value": round((1 << 20) / dt, 1),
    "mesh_reforms": m.counters.get("mesh_reforms", 0),
    "capacity_retries": m.counters.get("capacity_retries", 0),
}))
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", cpu_script], env=env, capture_output=True,
            text=True, timeout=600, check=True,
        )
        info = json.loads(r.stdout.strip().splitlines()[-1])
        _emit(
            "config5_zipf_1M_injected_failure_8dev_cpu_mesh",
            info["value"], "keys/sec", baseline=False,
            mesh_reforms=info["mesh_reforms"],
            capacity_retries=info["capacity_retries"],
            includes_reform_and_recompile=True,
        )
    except Exception as e:  # never let the capability line sink the artifact
        _emit(
            "config5_zipf_1M_injected_failure_8dev_cpu_mesh",
            0.0, "keys/sec", baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Exchange ladder (ISSUE 4, grown three-way by ISSUE 11): the adaptive
    # ppermute ring and the FUSED Pallas ring kernel against the one-shot
    # padded collective, on the 8-device cpu mesh (the schedules are the
    # same program on a single chip — the mesh is where an exchange exists
    # to compare).  The harness is `dsort bench --exchange-ab` — ONE copy
    # of the A/B contract, shared with `make bench-exchange-smoke` /
    # `make bench-fused-smoke` — re-emitted here with the cpu-mesh suffix;
    # rows: uniform int32 1M, zipf int64 1M (the capacity-retry workload),
    # TeraSort kv records, each carrying per-sort `bytes_on_wire` for the
    # lax schedules (every attempt charged: an overflowed padded dispatch
    # pays for the shipment it then re-did) plus an
    # `exchange_fused_vs_ring_*` row whose structural axis is
    # `dispatches_per_exchange` (lax ring P-1 -> fused 1).
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--exchange-ab", "--n", str(1 << 20), "--reps", "3",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        # Parse rows BEFORE judging the exit code: a bit-identical failure
        # exits 1 but its rows carry the diagnosis (which workload, and
        # bit_identical=false) — dropping them for a generic error line
        # would hide exactly what the A/B exists to catch.  Per-line
        # parsing, so one torn line (killed subprocess mid-print) cannot
        # take the complete rows down with it.
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"exchange A/B emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "exchange_ring_vs_alltoall_8dev_cpu_mesh", 0.0, "keys/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Multi-tenant serving-layer row (ISSUE 7): a mixed small/large
    # three-tenant workload through the real admission queue with
    # mesh-slice packing, on the 8-device cpu mesh.  The harness is
    # `dsort bench --serve-mixed` — ONE copy of the acceptance contract,
    # shared with `make serve-smoke` — re-emitted here with the cpu-mesh
    # suffix: jobs/s over the mixed workload, p95 queue wait and the
    # per-tenant fairness ratio from the journal's job_dequeued records,
    # the compiled-variant cache hit rate on the repeat-size jobs, and the
    # packed-vs-serial small-job speedup.
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--serve-mixed", "--n", str(400_000), "--reps", "1",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"serve-mixed emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "service_mixed_workload_8dev_cpu_mesh", 0.0, "jobs/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Introspection-plane cost row (ISSUE 9): the same ring sort with and
    # without journal+ledger+memwatch attached, plus the zipf-vs-uniform
    # skew-report margin.  The harness is `dsort bench --analyze-smoke` —
    # ONE copy of the contract, shared with `make profile-smoke` — and the
    # row proves observing costs < 5% of e2e (`introspection_ok`).
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--analyze-smoke", "--n", str(1 << 20), "--reps", "2",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"analyze-smoke emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "analyze_overhead_1M_8dev_cpu_mesh", 0.0, "frac",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Out-of-core wave-pipeline rows (ISSUE 10 / ROADMAP item 2): a binary
    # key file 8x the per-wave device budget sorts through the mesh wave
    # pipeline — overlap-on vs overlap-off A/B on the SAME data
    # (`overlap_speedup`), bit-identical output, plus a mid-wave fault
    # drill whose `resume_fraction` (re-sorted runs / total runs) must not
    # exceed one wave's share.  The harness is `dsort bench
    # --external-wave` — ONE copy of the contract, shared with `make
    # external-smoke`.
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--external-wave", "--n", str(1 << 23), "--reps", "3",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"external-wave emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "external_wave_sort_uniform_8M_8dev_cpu_mesh", 0.0, "keys/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Federated fleet row (ISSUE 12 / ROADMAP item 1): two local
    # mesh-owning agents behind a fleet controller over real TCP, mixed
    # tenants/sizes, locality-vs-random routing A/B — locality must beat
    # random on the fleet-wide variant-cache hit rate with bit-identical
    # outputs and the PR 7 fairness bound.  The harness is `dsort bench
    # --fleet-mixed` — ONE copy of the contract, shared with `make
    # fleet-smoke`.
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--fleet-mixed", "--n", str(200_000), "--reps", "1",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"fleet-mixed emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "fleet_mixed_workload_2agents_8dev_cpu_mesh", 0.0, "jobs/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Coded-redundancy rows (ISSUE 15 / ROADMAP item 3): the same zipf
    # workload at redundancy=1 vs 2, healthy vs one injected mid-ring
    # device loss, through SpmdScheduler on the 8-device cpu mesh.  The
    # uncoded faulted arm pays the re-form-and-re-run hit (the ~0.41x of
    # config5 above); the coded arm recovers by a LOCAL merge of replica
    # slots — `throughput_under_failure_ratio` must beat the re-run
    # baseline, with the healthy-path replica overhead reported alongside.
    # The harness is `dsort bench --coded-ab` — ONE copy of the contract,
    # shared with `make coded-smoke`.
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--coded-ab", "--n", str(1 << 20), "--reps", "3",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"coded A/B emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "coded_redundancy_failure_zipf_8dev_cpu_mesh", 0.0, "keys/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Closed-loop planner rows (ISSUE 16 / ROADMAP item 4): the same zipf
    # and uniform workloads with the exchange schedule hand-set to
    # alltoall, hand-set to ring, and planner-chosen (autotune on, knob
    # unset) — the planner's measured skew probe must pick ring on zipf /
    # alltoall on uniform, ship bit-identical keys, and land within 0.95x
    # of the best hand-set arm at this 1M ladder size (probe overhead must
    # not eat the win).  The harness is `dsort bench --autotune-ab` — ONE
    # copy of the contract, shared with `make autotune-smoke`.
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "dsort_tpu.cli", "bench",
                "--autotune-ab", "--n", str(1 << 20), "--reps", "3",
            ],
            env=env, capture_output=True, text=True, timeout=900,
        )
        rows = []
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        for row in rows:
            row["metric"] += "_8dev_cpu_mesh"
            _emit_line(row)
        if not rows:
            raise RuntimeError(
                f"autotune A/B emitted no rows (rc {r.returncode}): "
                + (r.stderr.strip().splitlines() or ["no stderr"])[-1][:160]
            )
    except Exception as e:  # the ladder must never sink the artifact
        _emit(
            "autotune_ab_zipf_int64_1M_8dev_cpu_mesh", 0.0, "keys/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Measure the host<->device link itself (VERDICT r5 weak #1 / next #4 —
    # the productized scratch/probe_transfer.py): warm H2D/D2H bandwidth on
    # a bulk buffer plus the small-transfer round-trip time.  The e2e
    # phase-split rows below derive `expected_transfer_s` from these, so
    # their host_fraction decomposes into link vs code from the artifact
    # alone.
    link = _probe_transfer(reps)

    # Phase split of one end-to-end SPMD sort: 'partition' (host prep + H2D)
    # and 'assemble' (D2H + host concat) are transfer-dominated through the
    # tunnel; 'spmd_sort' is the on-device program.
    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.utils.metrics import Metrics

    mesh = local_device_mesh()
    ss = SampleSort(mesh, JobConfig(local_kernel=kernel if chip == "tpu" else "lax"))

    def _phase_split(label: str, nkeys: int, seed: int) -> float:
        u = gen_uniform(nkeys, seed=seed)
        ss.sort(u)  # warm
        m = Metrics()
        t0 = time.perf_counter()
        ss.sort(u, metrics=m)
        total = time.perf_counter() - t0
        host_s = m.phase_s.get("partition", 0.0) + m.phase_s.get("assemble", 0.0)
        host_fraction = round(host_s / total, 3)
        extra = {}
        if link is not None:
            # The data plane moves the keys down once (partition) and up
            # once (assemble), with ~3 dispatch round-trips (input put,
            # execute+scalar fetch, range fetches).  Subtracting the link's
            # expected share from the measured host time attributes the
            # host_fraction: `_link` is what the measured bandwidth/RTT
            # predicts, `_code` is what the host phases cost beyond it.
            expected = (
                u.nbytes / link["h2d_bytes_per_s"]
                + u.nbytes / link["d2h_bytes_per_s"]
                + 3 * link["rtt_s"]
            )
            extra = {
                "expected_transfer_s": round(expected, 4),
                "host_fraction_link": round(min(expected, host_s) / total, 3),
                "host_fraction_code": round(
                    max(host_s - expected, 0.0) / total, 3
                ),
            }
        _emit(
            label, nkeys / total, "keys/sec",
            phases_seconds={
                k: round(v, 4) for k, v in sorted(m.phase_s.items())
            },
            # partition+assemble share of wall time.  Through the axon
            # relay this is TRANSFER-bound (~9-45 MB/s measured), not
            # host-memcpy-bound — the cpu-mesh line below isolates the
            # actual host work, and the *_link/*_code split above
            # attributes it in-artifact.
            host_fraction=host_fraction,
            **extra,
        )
        return total

    t_1m = _phase_split("spmd_sort_1M_end_to_end_phase_split", 1 << 20, 9)
    if chip == "tpu":
        # At-scale e2e: the data plane's host phases must not grow faster
        # than the device phase (VERDICT r4 next #1 'holds at scale').
        # The 2^26 run moves ~64x the 1M line's bytes through the relay,
        # so in a degraded tunnel window (observed: one such window took
        # ~25 min for this line alone) it would starve the REST of the
        # artifact — skip it with an honest line instead.
        if t_1m <= 5.0:
            _phase_split("spmd_sort_2p26_end_to_end_phase_split", 1 << 26, 10)
        else:
            _emit(
                "spmd_sort_2p26_end_to_end_phase_split", 0.0, "keys/sec",
                baseline=False,
                skipped=(
                    f"degraded tunnel window (1M e2e took {t_1m:.1f}s; the"
                    " 2^26 line moves ~64x the bytes) — see"
                    " BENCH_r05_preview.jsonl for the measured line"
                ),
            )

    # Device-resident e2e + on-device validation (VERDICT r5 next #5): the
    # path a real pipeline stage uses — the sorted array STAYS sharded on
    # the mesh (`keep_on_device` -> DeviceSortResult), and `dsort validate`
    # semantics (order + FNV multiset checksum) run as jitted shard_map
    # reductions with only scalars crossing to the host.  The phase-split
    # rows above measure the relay; this row is the sort.  Same 1M data as
    # the 1M phase split, so `speedup_vs_relay_e2e` is like-for-like.
    try:
        from dsort_tpu.models.validate import _multiset

        u1m = gen_uniform(1 << 20, seed=9)
        h = ss.sort(u1m, keep_on_device=True)  # warm the sort program
        h.validate_on_device()                 # warm the validator
        st, vt = [], []
        rep_v = None
        for _ in range(reps):
            t0 = time.perf_counter()
            h = ss.sort(u1m, keep_on_device=True)
            st.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            rep_v = h.validate_on_device()
            vt.append(time.perf_counter() - t0)
        dt, dtv = float(min(st)), float(min(vt))
        ok = bool(
            rep_v.sorted_ok
            and rep_v.records == len(u1m)
            and rep_v.checksum == _multiset(u1m, len(u1m), u1m.dtype.itemsize)
        )
        extra = {}
        if t_1m > 0:
            extra["speedup_vs_relay_e2e"] = round(t_1m / dt, 1)
        _emit(
            f"sort_e2e_device_resident_1M_{chip}{suffix}",
            (1 << 20) / dt, "keys/sec", validated_ok=ok, **extra,
        )
        # The on-device validate cost as its own metric: what `dsort
        # validate` semantics cost when nothing relays to the host.
        _emit(
            f"validate_on_device_1M_{chip}{suffix}",
            (1 << 20) / dtv, "keys/sec", baseline=False, validated_ok=ok,
        )
    except Exception as e:  # the no-relay lines must never sink the artifact
        _emit(
            f"sort_e2e_device_resident_1M_{chip}{suffix}", 0.0, "keys/sec",
            baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # The same phase split on the 8-device CPU mesh, where transfers are
    # memcpy: this isolates the data plane's genuine HOST work (pad
    # layout, overlapped range landing) from tunnel bandwidth.
    cpu_phase_script = r"""
import json, time
import jax
import numpy as np
from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_uniform
from dsort_tpu.parallel.mesh import local_device_mesh
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.utils.metrics import Metrics
ss = SampleSort(local_device_mesh(), JobConfig(local_kernel="lax"))
u = gen_uniform(1 << 20, seed=9)
for exch in ("alltoall", "ring"):
    ss.sort(u, exchange=exch)
    best = None
    for _ in range(3):
        m = Metrics()
        t0 = time.perf_counter()
        ss.sort(u, metrics=m, exchange=exch)
        total = time.perf_counter() - t0
        if best is None or total < best[0]:
            best = (total, m)
    total, m = best
    host_s = m.phase_s.get("partition", 0.0) + m.phase_s.get("assemble", 0.0)
    print(json.dumps({
        "exchange": exch,
        "value": round((1 << 20) / total, 1),
        "phases_seconds": {k: round(v, 4) for k, v in sorted(m.phase_s.items())},
        "host_fraction": round(host_s / total, 3),
    }))
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", cpu_phase_script], env=env,
            capture_output=True, text=True, timeout=600, check=True,
        )
        # One row per exchange schedule: the ring's phase split lands next
        # to the all_to_all's so the e2e overlap effect is in-artifact.
        for ln in r.stdout.strip().splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            info = json.loads(ln)
            suffix = "_ring" if info.get("exchange") == "ring" else ""
            _emit(
                f"spmd_sort_1M_phase_split_8dev_cpu_mesh{suffix}",
                info["value"], "keys/sec", baseline=False,
                phases_seconds=info["phases_seconds"],
                host_fraction=info["host_fraction"],
                exchange=info.get("exchange", "alltoall"),
            )
    except Exception as e:
        _emit(
            "spmd_sort_1M_phase_split_8dev_cpu_mesh",
            0.0, "keys/sec", baseline=False,
            error=(str(e).splitlines() or [repr(e)])[0][:200],
        )

    # Tunnel/HBM drift sentinel: lax.sort is HBM-pass-bound and swings ~2x
    # with relay health (the VMEM-resident block kernel held within ~1%
    # through the same swings), so re-measuring the SAME lax chain that
    # opened the suite flags whether later lines were taken in a degraded
    # window (observed r4: one window measured every chained program
    # 20-30x slow).  slowdown_at_end > ~1.5 means read the lines between
    # with suspicion; ~1.0 means the artifact is one coherent session.
    if hbm_sensor is not None and chip == "tpu":
        f_lax, per0, fixed0, chained0 = hbm_sensor
        per1, fixed1, chained1 = _slope_of(
            lambda c: _chain_total(f_lax, x, c, reps), c_short, chain
        )
        # Compare like with like: slope-vs-slope when both slopes are
        # valid, else chained-vs-chained (the fallback fires exactly in
        # the degraded windows this sensor exists to flag, and a chained
        # figure still carries overhead/c2 the slope cancels).
        if fixed0 is not None and fixed1 is not None:
            slowdown = per1 / per0
        else:
            slowdown = chained1 / chained0
        _emit(
            "tunnel_drift_sensor_lax_int32", n / per1, "keys/sec",
            baseline=False,
            **_slope_fields(per1, fixed1, chained1, n, c_short, chain),
            start_of_suite_keys_per_sec=round(n / per0, 1),
            slowdown_at_end=round(slowdown, 3),
        )


def _check_main(paths: list[str]) -> int:
    """``bench.py --check ARTIFACT...``: validate artifacts, report, exit.

    Needs no accelerator backend (and must not touch one: the checker runs
    in tier-1 CI against the in-tree ``BENCH_*.jsonl`` artifacts).
    """
    if not paths:
        print("usage: bench.py --check ARTIFACT [ARTIFACT...]", file=sys.stderr)
        return 2
    bad = 0
    for p in paths:
        errs = check_artifact(p)
        for e in errs:
            print(e, file=sys.stderr)
        print(f"{p}: {'OK' if not errs else f'{len(errs)} schema violations'}")
        bad += bool(errs)
    return 1 if bad else 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--check":
        sys.exit(_check_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "--compare":
        sys.exit(_compare_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "--history":
        sys.exit(_history_main(sys.argv[2:]))
    sys.exit(main())
