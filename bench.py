"""Benchmark entry point — one JSON line per metric, headline first.

Headline: single-chip sort throughput (keys/sec) on uniform random int32 at
2^24 keys, measured on the framework's own block-bitonic Pallas kernel
(``ops.block_sort`` — fused-pass network, see its module docstring), compared
against the reference system's measured end-to-end throughput of ~4.4e4
keys/s (BASELINE.md: 16,384 int32 in ~374 ms across 4 CPU workers over
localhost TCP — its maximum supported job size).

Secondary lines: the same workload on XLA's built-in ``lax.sort`` (the
round-1 headline — kept so the framework-kernel speedup is visible in the
same artifact), the 2^26 size (round 1's "memory cliff": lax.sort collapsed
there; the block kernel does not), the BASELINE config ladder (5 configs:
reference workload, 1M int32/int64 SPMD, TeraSort records, Zipf+failure),
and a phase split of one SPMD sort separating host<->device transfer from
on-chip compute.

Env knobs: DSORT_BENCH_N (default 2^24), DSORT_BENCH_REPS (default 3),
DSORT_BENCH_CHAIN (default 48 — the ~70-100 ms tunnel round-trip
divided by the chain length is the residual overhead per measured sort), DSORT_BENCH_KERNEL ("block" | "lax" | ...),
DSORT_BENCH_SUITE (default 1; 0 = headline lines only).

Timing methodology (unchanged from round 1): `block_until_ready` is
unreliable through the axon device tunnel (observed returning before
execution completes), and a single dispatch carries a ~70 ms host<->device
round-trip.  So (a) completion is forced by a tiny device->host slice copy,
and (b) `chain` data-dependent sorts run inside ONE jitted program (each
iteration re-sorts the previous result XOR the loop index; comparator
networks are data-oblivious, so chaining is distribution-fair) and the
per-sort time is total/chain.  min over reps, not median: tunnel jitter is
one-sided additive noise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_KEYS_PER_SEC = 16_384 / 0.374  # BASELINE.md measured, ~4.38e4


def _ensure_responsive_backend() -> None:
    """Guard against a wedged accelerator runtime.

    Backend init can hang indefinitely if the device tunnel is in a bad state
    (observed: a killed client can leave the chip claim stuck for a long
    time).  Probe device init in a subprocess with a timeout; on failure,
    re-exec this benchmark on the CPU backend so the driver always gets its
    JSON lines instead of a hang.
    """
    if os.environ.get("DSORT_BENCH_NO_PROBE"):
        return
    timeout = float(os.environ.get("DSORT_BENCH_DEVICE_TIMEOUT", 180))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            check=True,
        )
        return  # backend healthy; run in-process normally
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the TPU site hook
    env["DSORT_BENCH_NO_PROBE"] = "1"
    env["DSORT_BENCH_FALLBACK"] = "cpu"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _emit(metric: str, value: float, unit: str, **extra) -> None:
    line = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(value / REFERENCE_KEYS_PER_SEC, 2),
    }
    line.update(extra)
    print(json.dumps(line), flush=True)


def _timed_chain(sort_fn, x, n: int, chain: int, reps: int) -> float:
    """Per-sort seconds for `sort_fn` under the chained methodology."""
    import jax
    from jax import lax

    f = jax.jit(
        lambda a: lax.fori_loop(0, chain, lambda i, v: sort_fn(v ^ i), a)
    )
    y = f(x)  # compile + warm
    out_head = np.asarray(y[: 1 << 16])  # forces completion
    assert (np.diff(out_head) >= 0).all(), "bench output not sorted"
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _ = np.asarray(f(x)[-1:])  # tiny D2H copy = true completion barrier
        times.append(time.perf_counter() - t0)
    return float(min(times)) / chain


def main() -> None:
    _ensure_responsive_backend()

    import jax

    # Persistent compilation cache: the Pallas kernel set compiles in ~1 min
    # cold; cached reloads take seconds (verified through the axon remote
    # compiler).  Harmless on CPU.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dsort_tpu.ops.local_sort import sort_with_kernel

    n = int(os.environ.get("DSORT_BENCH_N", 1 << 24))
    reps = int(os.environ.get("DSORT_BENCH_REPS", 3))
    chain = int(os.environ.get("DSORT_BENCH_CHAIN", 48))
    if chain < 1:
        raise SystemExit("DSORT_BENCH_CHAIN must be >= 1")
    chip = jax.devices()[0].platform
    kernel = os.environ.get("DSORT_BENCH_KERNEL", "block")
    if chip != "tpu" and kernel == "block":
        # The Pallas kernel only *interprets* off-TPU — orders of magnitude
        # slow; the CPU fallback measures lax so the driver still gets lines.
        kernel = "lax"
    suffix = "_fallback" if os.environ.get("DSORT_BENCH_FALLBACK") else ""

    rng = np.random.default_rng(0)
    host = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    x = jax.numpy.asarray(host)

    # Headline: the framework kernel.
    dt = _timed_chain(lambda v: sort_with_kernel(v, kernel), x, n, chain, reps)
    _emit(
        f"sort_throughput_int32_{n}_keys_single_chip_{chip}{suffix}",
        n / dt,
        "keys/sec",
        kernel=kernel,
    )

    if os.environ.get("DSORT_BENCH_SUITE", "1") != "1":
        return

    # The round-1 headline kernel (XLA lax.sort) on the same workload, for a
    # like-for-like speedup record in the same artifact.
    if kernel != "lax":
        dt_lax = _timed_chain(
            lambda v: sort_with_kernel(v, "lax"), x, n, chain, reps
        )
        _emit(
            f"sort_throughput_int32_{n}_keys_single_chip_{chip}_lax_kernel",
            n / dt_lax,
            "keys/sec",
            kernel="lax",
        )

    # 2^26: round 1's memory cliff (lax.sort fell to ~48 Mkeys/s there).
    if chip == "tpu":
        n26 = 1 << 26
        big = jax.numpy.asarray(
            rng.integers(-(2**31), 2**31 - 1, n26, dtype=np.int64).astype(
                np.int32
            )
        )
        dt26 = _timed_chain(
            lambda v: sort_with_kernel(v, kernel), big, n26, max(chain // 4, 1), reps
        )
        _emit(
            f"sort_throughput_int32_{n26}_keys_single_chip_{chip}",
            n26 / dt26,
            "keys/sec",
            kernel=kernel,
        )
        del big

    # BASELINE config ladder (5 lines) — end-to-end host->host timings of the
    # public SampleSort API, so these *include* the tunnel round-trip.
    import argparse

    from dsort_tpu import cli as _cli

    jax.config.update("jax_enable_x64", True)  # config3 sorts int64 keys
    _cli._bench_suite(argparse.Namespace(reps=reps))

    # Phase split of one end-to-end SPMD sort: 'partition' (host prep + H2D)
    # and 'assemble' (D2H + host concat) are transfer-dominated through the
    # tunnel; 'spmd_sort' is the on-device program.
    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.utils.metrics import Metrics

    mesh = local_device_mesh()
    ss = SampleSort(mesh, JobConfig(local_kernel=kernel if chip == "tpu" else "lax"))
    u = gen_uniform(1 << 20, seed=9)
    ss.sort(u)  # warm
    m = Metrics()
    t0 = time.perf_counter()
    ss.sort(u, metrics=m)
    total = time.perf_counter() - t0
    _emit(
        "spmd_sort_1M_end_to_end_phase_split",
        (1 << 20) / total,
        "keys/sec",
        phases_seconds={
            k: round(v, 4) for k, v in sorted(m.phase_s.items())
        },
    )


if __name__ == "__main__":
    sys.exit(main())
